"""Direct actor calls: the head is out of a.m.remote() (round-4 ask #1).

Reference: src/ray/core_worker/transport/actor_task_submitter.cc:482
PushActorTask + sequential_actor_submit_queue.cc — method calls go straight
from the caller to the actor's node, sequence-ordered; the control plane
keeps only the lifecycle FSM. Here: head.tasks must hold ONLY the actor
CREATION record (one per incarnation), never per-call records.
"""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core import runtime as runtime_mod


def _head():
    return runtime_mod.get_current_runtime().head


@ray_tpu.remote
class Counter:
    def __init__(self):
        self.values = []

    def add(self, v):
        self.values.append(v)
        return len(self.values)

    def get(self):
        return list(self.values)

    def boom(self):
        raise ValueError("actor method failed")

    def pid(self):
        import os

        return os.getpid()


class TestDirectActorLocal:
    def setup_method(self):
        ray_tpu.init(num_cpus=2)

    def teardown_method(self):
        ray_tpu.shutdown()

    def test_no_per_call_head_records(self):
        c = Counter.remote()
        refs = [c.add.remote(i) for i in range(50)]
        assert ray_tpu.get(refs)[-1] == 50
        # only the CREATION task transited the head
        head = _head()
        assert len(head.tasks) == 1, f"head saw {len(head.tasks)} records"
        assert all(r.spec.is_actor_creation for r in head.tasks.values())

    def test_ordering_preserved(self):
        c = Counter.remote()
        for i in range(200):
            c.add.remote(i)
        assert ray_tpu.get(c.get.remote()) == list(range(200))

    def test_method_error_propagates(self):
        c = Counter.remote()
        with pytest.raises(Exception, match="actor method failed"):
            ray_tpu.get(c.boom.remote())
        # actor still alive after a user error
        assert ray_tpu.get(c.add.remote(1)) == 1

    def test_ref_args_into_actor_calls(self):
        c = Counter.remote()
        dep = ray_tpu.put(41)

        @ray_tpu.remote
        def plus_one(x):
            return x + 1

        pending = plus_one.remote(dep)  # direct task; may still be running
        c.add.remote(pending)           # actor call deferred on the dep
        c.add.remote(99)                # must NOT overtake the deferred one
        assert ray_tpu.get(c.get.remote(), timeout=60) == [42, 99]
        assert len(_head().tasks) == 1

    def test_calls_before_actor_ready_are_buffered(self):
        @ray_tpu.remote
        class Slow:
            def __init__(self):
                time.sleep(1.0)
                self.v = []

            def add(self, x):
                self.v.append(x)
                return list(self.v)

        s = Slow.remote()
        refs = [s.add.remote(i) for i in range(5)]  # submitted pre-ALIVE
        assert ray_tpu.get(refs[-1], timeout=60) == [0, 1, 2, 3, 4]
        assert len(_head().tasks) == 1

    def test_kill_fails_inflight_and_future_calls(self):
        @ray_tpu.remote
        class Sleeper:
            def nap(self, t):
                time.sleep(t)
                return "ok"

        s = Sleeper.remote()
        assert ray_tpu.get(s.nap.remote(0)) == "ok"
        ref = s.nap.remote(30)
        time.sleep(0.5)
        ray_tpu.kill(s)
        with pytest.raises(Exception):
            ray_tpu.get(ref, timeout=60)
        with pytest.raises(Exception) as ei:
            ray_tpu.get(s.nap.remote(0), timeout=60)
        if isinstance(ei.value, ray_tpu.ActorDiedError):
            # attributed death cause (node/pid), never a bare timeout
            assert "node " in str(ei.value), str(ei.value)

    def test_async_actor_direct(self):
        @ray_tpu.remote
        class Async:
            async def work(self, i):
                import asyncio

                await asyncio.sleep(0.01)
                return i * 2

        a = Async.options(max_concurrency=8).remote()
        out = ray_tpu.get([a.work.remote(i) for i in range(16)], timeout=60)
        assert out == [i * 2 for i in range(16)]
        assert len(_head().tasks) == 1


class TestDirectActorEdgeCases:
    def setup_method(self):
        ray_tpu.init(num_cpus=2)

    def teardown_method(self):
        ray_tpu.shutdown()

    def test_streaming_call_behind_deferred_dep(self):
        """A streaming call submitted while a dep-deferred direct call is
        queued ahead of it: the ordered route gates the stream behind the
        deferred call, and both complete on the direct path (round 5:
        streaming is direct-eligible, head_pin is gone)."""
        @ray_tpu.remote
        class Gen:
            def consume(self, x):
                return x + 1

            def stream(self, n):
                for i in range(n):
                    yield i

        @ray_tpu.remote
        def slow_dep():
            time.sleep(1.0)
            return 10

        g = Gen.remote()
        r1 = g.consume.remote(slow_dep.remote())  # deferred on the dep
        items = list(g.stream.options(
            num_returns="streaming").remote(3))    # head-pins the actor
        assert [ray_tpu.get(i) for i in items] == [0, 1, 2]
        assert ray_tpu.get(r1, timeout=60) == 11

    def test_cancel_deferred_call_unblocks_queue(self):
        """Cancelling a dep-deferred actor call must not wedge later
        calls behind it in the ordered queue (round-4 review finding)."""
        @ray_tpu.remote
        def never_quick():
            time.sleep(5)
            return 1

        c = Counter.remote()
        r1 = c.add.remote(never_quick.remote())  # deferred
        ray_tpu.cancel(r1)
        assert ray_tpu.get(c.add.remote(7), timeout=30) == 1
        with pytest.raises(Exception):
            ray_tpu.get(r1, timeout=30)


class TestDirectActorRestart:
    def test_restart_during_calls(self):
        """Queued calls flush to the restarted actor or fail per
        max_task_retries (VERDICT round-3 ask #1 'done' bar)."""
        ray_tpu.init(num_cpus=2)
        try:
            import os

            @ray_tpu.remote
            class Crashy:
                def __init__(self):
                    self.n = 0

                def bump(self):
                    self.n += 1
                    return self.n

                def slow_bump(self):
                    time.sleep(3)
                    self.n += 1
                    return self.n

                def pid(self):
                    return os.getpid()

            c = Crashy.options(max_restarts=1, max_task_retries=2).remote()
            assert ray_tpu.get(c.bump.remote(), timeout=60) == 1
            pid = ray_tpu.get(c.pid.remote(), timeout=60)
            inflight = c.slow_bump.remote()  # running when the crash hits
            time.sleep(0.5)
            os.kill(pid, 9)  # hard-crash the incarnation from outside
            time.sleep(0.3)
            # calls during/after the crash retry onto the new incarnation,
            # in order: the retried slow_bump lands first
            out = ray_tpu.get([c.bump.remote() for _ in range(3)],
                              timeout=120)
            assert out == [2, 3, 4]  # fresh state + retried slow_bump
            assert ray_tpu.get(inflight, timeout=60) == 1
        finally:
            ray_tpu.shutdown()

    def test_no_retries_raises_actor_died(self):
        ray_tpu.init(num_cpus=2)
        try:
            @ray_tpu.remote
            class Crashy2:
                def spin_die(self):
                    import os
                    import time as _t

                    _t.sleep(0.2)
                    os._exit(1)

            c = Crashy2.options(max_restarts=0).remote()
            ref = c.spin_die.remote()
            with pytest.raises(Exception):
                ray_tpu.get(ref, timeout=60)
        finally:
            ray_tpu.shutdown()


class TestDirectActorMultiNode:
    def test_calls_route_to_peer_node_actor(self):
        cluster = Cluster(head_node_args={"num_cpus": 1})
        n2 = cluster.add_node(num_cpus=2, resources={"spot": 1})
        try:
            c = Counter.options(resources={"spot": 0.1}).remote()
            refs = [c.add.remote(i) for i in range(30)]
            assert ray_tpu.get(refs, timeout=120)[-1] == 30
            assert ray_tpu.get(c.get.remote()) == list(range(30))
            head = _head()
            assert len(head.tasks) == 1
            # the actor really lives on the peer node
            arec = head.actors[c._actor_id]
            assert arec.node_hex == n2.hex
        finally:
            cluster.shutdown()

    def test_calls_route_to_daemon_actor_over_tcp(self):
        cluster = Cluster(head_node_args={"num_cpus": 1})
        n2 = cluster.add_node(num_cpus=2, resources={"spot": 1},
                              separate_process=True)
        try:
            c = Counter.options(resources={"spot": 0.1}).remote()
            refs = [c.add.remote(i) for i in range(30)]
            assert ray_tpu.get(refs, timeout=180)[-1] == 30
            assert ray_tpu.get(c.get.remote(), timeout=60) == list(range(30))
            head = _head()
            assert len(head.tasks) == 1
            arec = head.actors[c._actor_id]
            assert arec.node_hex == n2.hex
        finally:
            cluster.shutdown()

    def test_worker_submits_actor_calls_directly(self):
        """A task (worker-side owner) holding an actor handle calls it
        without creating head records."""
        ray_tpu.init(num_cpus=3)
        try:
            c = Counter.remote()

            @ray_tpu.remote
            def caller(handle, base):
                refs = [handle.add.remote(base + i) for i in range(5)]
                return ray_tpu.get(refs)[-1]

            assert ray_tpu.get(caller.remote(c, 0), timeout=120) == 5
            head = _head()
            assert len(head.tasks) == 1  # creation only
        finally:
            ray_tpu.shutdown()
