"""Local mode (init(local_mode=True)): inline execution with full API
semantics. Reference: ray.init(local_mode=True) debugging mode tests."""

import pytest

import ray_tpu


@pytest.fixture
def local_mode():
    ray_tpu.init(local_mode=True)
    yield
    ray_tpu.shutdown()


class TestLocalMode:
    def test_tasks_and_objects(self, local_mode):
        @ray_tpu.remote
        def add(a, b):
            return a + b

        ref = ray_tpu.put(40)
        assert ray_tpu.get(add.remote(ref, 2)) == 42
        # chained refs
        assert ray_tpu.get(add.remote(add.remote(1, 2), 3)) == 6
        ready, not_ready = ray_tpu.wait([add.remote(1, 1)], num_returns=1)
        assert len(ready) == 1 and not not_ready

    def test_errors_reraise_at_get(self, local_mode):
        @ray_tpu.remote
        def boom():
            raise ValueError("bad")

        ref = boom.remote()  # executes inline but defers the raise
        with pytest.raises(Exception):
            ray_tpu.get(ref)

    def test_actors_and_named_actors(self, local_mode):
        @ray_tpu.remote
        class Counter:
            def __init__(self, start=0):
                self.n = start

            def add(self, k):
                self.n += k
                return self.n

        c = Counter.options(name="ctr").remote(10)
        assert ray_tpu.get(c.add.remote(5)) == 15
        c2 = ray_tpu.get_actor("ctr")
        assert ray_tpu.get(c2.add.remote(1)) == 16
        ray_tpu.kill(c)
        with pytest.raises(Exception):
            ray_tpu.get_actor("ctr")

    def test_multiple_returns(self, local_mode):
        @ray_tpu.remote(num_returns=2)
        def pair():
            return 1, 2

        a, b = pair.remote()
        assert ray_tpu.get(a) == 1 and ray_tpu.get(b) == 2

    def test_streaming_generator(self, local_mode):
        @ray_tpu.remote(num_returns="streaming")
        def gen(n):
            for i in range(n):
                yield i * i

        out = [ray_tpu.get(r) for r in gen.remote(4)]
        assert out == [0, 1, 4, 9]

    def test_nested_tasks(self, local_mode):
        @ray_tpu.remote
        def inner(x):
            return x * 2

        @ray_tpu.remote
        def outer(x):
            return ray_tpu.get(inner.remote(x)) + 1

        assert ray_tpu.get(outer.remote(10)) == 21

    def test_cluster_info(self, local_mode):
        assert ray_tpu.cluster_resources().get("CPU", 0) >= 1
        assert ray_tpu.nodes()[0]["Alive"]

    def test_streaming_midstream_error_surfaces(self, local_mode):
        @ray_tpu.remote(num_returns="streaming")
        def gen():
            yield 1
            yield 2
            raise ValueError("mid-stream")

        it = gen.remote()
        got = []
        with pytest.raises(Exception):
            for r in it:
                got.append(ray_tpu.get(r))
        assert got == [1, 2]

    def test_duplicate_named_actor_rejected(self, local_mode):
        @ray_tpu.remote
        class A:
            pass

        A.options(name="dup").remote()
        with pytest.raises(ValueError):
            A.options(name="dup").remote()

    def test_num_returns_mismatch_is_clear_error(self, local_mode):
        @ray_tpu.remote(num_returns=3)
        def two():
            return 1, 2

        refs = two.remote()
        with pytest.raises(Exception, match="expected num_returns"):
            ray_tpu.get(refs[0])


def test_protocol_version_check():
    from ray_tpu.core.protocol import (PROTOCOL_VERSION,
                                       ProtocolVersionError, check_protocol)

    check_protocol({"proto": PROTOCOL_VERSION})  # no raise
    with pytest.raises(ProtocolVersionError):
        check_protocol({"proto": PROTOCOL_VERSION + 1})
    with pytest.raises(ProtocolVersionError):
        check_protocol({})  # pre-versioning peer


class TestLocalModeDeferredErrors:
    def test_failing_actor_init_defers_to_get(self, local_mode):
        @ray_tpu.remote
        class Broken:
            def __init__(self):
                raise RuntimeError("init boom")

            def m(self):
                return 1

        b = Broken.remote()  # must NOT raise here (cluster parity)
        with pytest.raises(Exception, match="init boom|dead"):
            ray_tpu.get(b.m.remote())

    def test_missing_method_defers_to_get(self, local_mode):
        @ray_tpu.remote
        class A:
            def m(self):
                return 1

        a = A.remote()
        ref = a.nope.remote()  # must NOT raise here
        with pytest.raises(Exception):
            ray_tpu.get(ref)

    def test_streaming_prestart_error_raises(self, local_mode):
        @ray_tpu.remote(num_returns="streaming")
        def gen(n):
            yield n

        it = gen.remote()  # wrong arity: fails before iteration starts
        with pytest.raises(Exception):
            for r in it:
                ray_tpu.get(r)

    def test_streaming_on_dead_actor_raises(self, local_mode):
        @ray_tpu.remote
        class G:
            def gen(self, n):
                yield n

        g = G.remote()
        ray_tpu.kill(g)
        # streaming call on a dead actor must raise, not iterate empty
        stream = g.gen.options(num_returns="streaming").remote(1)
        with pytest.raises(Exception):
            for r in stream:
                ray_tpu.get(r)
