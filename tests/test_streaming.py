"""Streaming generators: num_returns="streaming" on tasks and actors.

Reference: _raylet.pyx:1074-1317 streaming generator plumbing +
ObjectRefGenerator semantics (incremental consumption, mid-stream errors).
"""

import time

import pytest

import ray_tpu
from ray_tpu.core.exceptions import TaskError


def test_task_stream_basic(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    g = gen.remote(5)
    assert [ray_tpu.get(r) for r in g] == [0, 10, 20, 30, 40]
    # completed() resolves to the item count
    assert ray_tpu.get(g.completed()) == 5


def test_stream_incremental_consumption(ray_start_regular):
    """Items are consumable while the producer is still running."""
    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        for i in range(4):
            time.sleep(0.3)
            yield i

    @ray_tpu.remote
    def warmup():
        return 1

    ray_tpu.get(warmup.remote())  # absorb worker cold start
    t0 = time.monotonic()
    it = iter(slow_gen.remote())
    first = ray_tpu.get(next(it))
    elapsed = time.monotonic() - t0
    assert first == 0
    assert elapsed < 1.0, f"first item took {elapsed:.2f}s (not incremental)"
    assert [ray_tpu.get(r) for r in it] == [1, 2, 3]


def test_stream_mid_error(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def bad_gen():
        yield 1
        raise ValueError("boom")

    it = iter(bad_gen.remote())
    assert ray_tpu.get(next(it)) == 1
    with pytest.raises(TaskError):
        ray_tpu.get(next(it))


def test_stream_empty(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def empty():
        return
        yield  # pragma: no cover

    assert list(empty.remote()) == []


@pytest.mark.slow  # >5s on the 1-core box: full-tier only (tier-1 wall budget)
def test_stream_empty_stress(ray_start_regular):
    """Regression: empty-stream EOF delivery under GC + task load.

    Round-5 full-suite runs hung forever in test_stream_empty (zero CPU):
    ``ObjectRef.__del__`` ran ``remove_local_ref`` inside the garbage
    collector, which can fire on a thread already holding the
    DirectTaskManager lock — self-deadlocking the completion path and
    losing the stream's EOF (an empty stream's ONLY signal is the EOF).
    Drops are now handed to a reaper thread; this loops empty-stream
    creation under background load with forced GC to keep the original
    interleaving covered.
    """
    import gc
    import threading

    @ray_tpu.remote(num_returns="streaming")
    def empty():
        return
        yield  # pragma: no cover

    @ray_tpu.remote
    def busy(i):
        return [i] * 64

    stop = threading.Event()
    errors = []

    def load():
        while not stop.is_set():
            try:
                ray_tpu.get([busy.remote(i) for i in range(4)], timeout=60)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    t = threading.Thread(target=load, daemon=True)
    t.start()
    try:
        for i in range(20):
            # churn refs so the GC has ObjectRefs to finalize mid-loop
            assert list(empty.remote()) == []
            if i % 5 == 0:
                gc.collect()
    finally:
        stop.set()
        t.join(timeout=30)
    assert not errors, errors
    # the queued __del__ drops must drain without wedging the runtime
    from ray_tpu.core.object_ref import _drop_queue, flush_pending_drops

    flush_pending_drops(timeout=10.0)
    assert not _drop_queue


def test_actor_method_stream(ray_start_regular):
    @ray_tpu.remote
    class A:
        def stream(self, n):
            for i in range(n):
                yield chr(65 + i)

    a = A.remote()
    g = a.stream.options(num_returns="streaming").remote(3)
    assert [ray_tpu.get(r) for r in g] == ["A", "B", "C"]


def test_stream_consumed_inside_task(ray_start_regular):
    """A worker task can consume another task's stream (worker-side
    stream_next goes through the bounded-rounds RPC path)."""
    @ray_tpu.remote(num_returns="streaming")
    def source():
        for i in range(3):
            yield i + 1

    @ray_tpu.remote
    def consume(g):
        return sum(ray_tpu.get(r) for r in g)

    assert ray_tpu.get(consume.remote(source.remote())) == 6


def test_stream_large_items(ray_start_regular):
    """Items above the inline threshold go through the arena."""
    import numpy as np

    @ray_tpu.remote(num_returns="streaming")
    def big_gen():
        for i in range(3):
            yield np.full(200_000, i, dtype=np.int64)  # 1.6MB each

    vals = [ray_tpu.get(r) for r in big_gen.remote()]
    assert [int(v[0]) for v in vals] == [0, 1, 2]


def test_direct_stream_zero_head_records(ray_start_regular):
    """Round-5 invariant: streaming rides the direct path end to end —
    a task stream and an actor-call stream leave ZERO head task records
    beyond the actor creation, and no head stream records at all
    (items ride the direct reply chain to the owner)."""
    from ray_tpu.core import runtime as runtime_mod

    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i

    @ray_tpu.remote
    class A:
        def stream(self, n):
            for i in range(n):
                yield i * 2

    head = runtime_mod.get_current_runtime().head
    a = A.remote()
    assert ray_tpu.get(a.stream.options(  # warm the actor
        num_returns="streaming").remote(1).completed()) == 1
    before = len(head.tasks)

    assert [ray_tpu.get(r) for r in gen.remote(4)] == [0, 1, 2, 3]
    assert [ray_tpu.get(r)
            for r in a.stream.options(
                num_returns="streaming").remote(3)] == [0, 2, 4]

    assert len(head.tasks) == before  # no new head task records
    assert not head.streams           # no head stream records


def test_stream_across_daemon_nodes(ray_start_cluster):
    """Stream items hop the peer mesh: the producer actor lives on a
    separate-process daemon, the driver consumes — item announcements
    ride executor-worker -> daemon node -> head node -> owner, with the
    completion FIFO behind them."""
    cluster = ray_start_cluster
    # capacity 2: the Producer actor holds one unit for life, the big()
    # task needs the other
    cluster.add_node(num_cpus=2, resources={"там": 2},
                     separate_process=True)

    @ray_tpu.remote(resources={"там": 1})
    class Producer:
        def stream(self, n):
            for i in range(n):
                yield ("item", i)

    p = Producer.remote()
    g = p.stream.options(num_returns="streaming").remote(5)
    assert [ray_tpu.get(r) for r in g] == [("item", i) for i in range(5)]

    # large items cross the mesh via the store path
    import numpy as np

    @ray_tpu.remote(resources={"там": 1}, num_returns="streaming")
    def big():
        for i in range(2):
            yield np.full(150_000, i, dtype=np.int64)

    vals = [ray_tpu.get(r) for r in big.remote()]
    assert [int(v[0]) for v in vals] == [0, 1]


@pytest.mark.slow  # >5s on the 1-core box: full-tier only (tier-1 wall budget)
def test_serve_streaming_and_data_split_head_free(ray_start_regular):
    """Round-5 verdict ask #1 "done" criteria: a Serve streaming response
    and a Data streaming_split iterator both run with zero new head task
    records and zero head stream records."""
    from ray_tpu import serve
    from ray_tpu.core import runtime as runtime_mod

    head = runtime_mod.get_current_runtime().head

    @serve.deployment(stream=True)
    class Streamer:
        def __call__(self, n):
            for i in range(n):
                yield f"chunk{i}"

    h = serve.run(Streamer.bind())
    assert list(h.options(stream=True).remote(2)) == ["chunk0", "chunk1"]
    before = len(head.tasks)
    assert list(h.options(stream=True).remote(3)) == [
        "chunk0", "chunk1", "chunk2"]
    assert len(head.tasks) == before, "serve streaming touched the head"
    assert not head.streams
    serve.shutdown()

    import ray_tpu.data as rdata

    ds = rdata.range(20)
    it = ds.streaming_split(1)[0]
    total = sum(sum(b["id"]) for b in it.iter_batches(batch_size=5))
    assert total == sum(range(20))
    assert not head.streams, "streaming_split left head stream records"
