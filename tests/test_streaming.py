"""Streaming generators: num_returns="streaming" on tasks and actors.

Reference: _raylet.pyx:1074-1317 streaming generator plumbing +
ObjectRefGenerator semantics (incremental consumption, mid-stream errors).
"""

import time

import pytest

import ray_tpu
from ray_tpu.core.exceptions import TaskError


def test_task_stream_basic(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    g = gen.remote(5)
    assert [ray_tpu.get(r) for r in g] == [0, 10, 20, 30, 40]
    # completed() resolves to the item count
    assert ray_tpu.get(g.completed()) == 5


def test_stream_incremental_consumption(ray_start_regular):
    """Items are consumable while the producer is still running."""
    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        for i in range(4):
            time.sleep(0.3)
            yield i

    @ray_tpu.remote
    def warmup():
        return 1

    ray_tpu.get(warmup.remote())  # absorb worker cold start
    t0 = time.monotonic()
    it = iter(slow_gen.remote())
    first = ray_tpu.get(next(it))
    elapsed = time.monotonic() - t0
    assert first == 0
    assert elapsed < 1.0, f"first item took {elapsed:.2f}s (not incremental)"
    assert [ray_tpu.get(r) for r in it] == [1, 2, 3]


def test_stream_mid_error(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def bad_gen():
        yield 1
        raise ValueError("boom")

    it = iter(bad_gen.remote())
    assert ray_tpu.get(next(it)) == 1
    with pytest.raises(TaskError):
        ray_tpu.get(next(it))


def test_stream_empty(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def empty():
        return
        yield  # pragma: no cover

    assert list(empty.remote()) == []


def test_actor_method_stream(ray_start_regular):
    @ray_tpu.remote
    class A:
        def stream(self, n):
            for i in range(n):
                yield chr(65 + i)

    a = A.remote()
    g = a.stream.options(num_returns="streaming").remote(3)
    assert [ray_tpu.get(r) for r in g] == ["A", "B", "C"]


def test_stream_consumed_inside_task(ray_start_regular):
    """A worker task can consume another task's stream (worker-side
    stream_next goes through the bounded-rounds RPC path)."""
    @ray_tpu.remote(num_returns="streaming")
    def source():
        for i in range(3):
            yield i + 1

    @ray_tpu.remote
    def consume(g):
        return sum(ray_tpu.get(r) for r in g)

    assert ray_tpu.get(consume.remote(source.remote())) == 6


def test_stream_large_items(ray_start_regular):
    """Items above the inline threshold go through the arena."""
    import numpy as np

    @ray_tpu.remote(num_returns="streaming")
    def big_gen():
        for i in range(3):
            yield np.full(200_000, i, dtype=np.int64)  # 1.6MB each

    vals = [ray_tpu.get(r) for r in big_gen.remote()]
    assert [int(v[0]) for v in vals] == [0, 1, 2]
