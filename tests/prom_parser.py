"""Minimal Prometheus exposition-format parser (test utility).

Strict enough to catch the real failure modes of a hand-rolled renderer:
unescaped quotes/backslashes/newlines in label values, malformed label
blocks, bad metric names, non-numeric values, and malformed comment
lines. Returns parsed samples so tests can assert label round-trips.
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")
_ESCAPES = {"\\": "\\", '"': '"', "n": "\n"}


class PromParseError(ValueError):
    pass


def _err(lineno: int, msg: str, line: str) -> "PromParseError":
    return PromParseError(f"line {lineno}: {msg}: {line!r}")


def _parse_labels(line: str, i: int, lineno: int) -> Tuple[Dict[str, str], int]:
    """Parse a ``{k="v",...}`` block starting at ``line[i] == '{'``;
    returns (labels, index past the closing brace)."""
    labels: Dict[str, str] = {}
    i += 1  # past '{'
    try:
        while line[i] != "}":
            j = i
            while line[j] not in "=,}":
                j += 1
            lname = line[i:j]
            if not _LABEL.match(lname):
                raise _err(lineno, f"bad label name {lname!r}", line)
            if line[j] != "=":
                raise _err(lineno, "expected '=' after label name", line)
            j += 1
            if line[j] != '"':
                raise _err(lineno, "label value must be quoted", line)
            j += 1
            buf: List[str] = []
            while line[j] != '"':
                c = line[j]
                if c == "\\":
                    esc = line[j + 1]
                    if esc not in _ESCAPES:
                        raise _err(lineno, f"bad escape \\{esc}", line)
                    buf.append(_ESCAPES[esc])
                    j += 2
                else:
                    buf.append(c)
                    j += 1
            labels[lname] = "".join(buf)
            j += 1  # past closing quote
            if line[j] == ",":
                i = j + 1
            elif line[j] == "}":
                i = j
            else:
                raise _err(lineno, "expected ',' or '}' after label", line)
    except IndexError:
        raise _err(lineno, "truncated label block "
                   "(unescaped quote or newline?)", line) from None
    return labels, i + 1


def _parse_sample(line: str, lineno: int) -> Tuple[str, Dict[str, str], float]:
    i = 0
    while i < len(line) and (line[i].isalnum() or line[i] in "_:"):
        i += 1
    name = line[:i]
    if not _NAME.match(name):
        raise _err(lineno, f"bad metric name {name!r}", line)
    labels: Dict[str, str] = {}
    if i < len(line) and line[i] == "{":
        labels, i = _parse_labels(line, i, lineno)
    if i >= len(line) or line[i] != " ":
        raise _err(lineno, "expected space before value", line)
    rest = line[i + 1:].split()
    if not rest or len(rest) > 2:  # value [timestamp]
        raise _err(lineno, "expected 'value [timestamp]'", line)
    try:
        value = float(rest[0].replace("+Inf", "inf").replace("-Inf", "-inf"))
    except ValueError:
        raise _err(lineno, f"bad value {rest[0]!r}", line) from None
    if len(rest) == 2:
        try:
            int(rest[1])
        except ValueError:
            raise _err(lineno, f"bad timestamp {rest[1]!r}", line) from None
    return name, labels, value


def parse_histograms(text: str) -> Dict[str, List[dict]]:
    """Strictly parse every histogram FAMILY in exposition text.

    For each ``# TYPE <name> histogram`` family, group its
    ``_bucket``/``_sum``/``_count`` samples by label set (minus ``le``)
    and validate Prometheus histogram conformance:

    - all three sample kinds present for every series,
    - every ``le`` value parses as a float or ``+Inf``,
    - a ``+Inf`` bucket exists and equals ``_count``,
    - cumulative bucket counts are non-decreasing with increasing ``le``.

    Returns {family: [{"labels", "buckets" (le->count), "sum",
    "count"}, ...]}; raises :class:`PromParseError` on any violation.
    """
    hist_families = set()
    for line in text.split("\n"):
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) >= 4 and parts[3] == "histogram":
                hist_families.add(parts[2])

    series: Dict[Tuple[str, tuple], dict] = {}
    for name, labels, value in parse_exposition(text):
        family = kind = None
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base in hist_families:
                family, kind = base, suffix
                break
        if family is None:
            continue
        key_labels = {k: v for k, v in labels.items() if k != "le"}
        key = (family, tuple(sorted(key_labels.items())))
        s = series.setdefault(key, {"labels": key_labels, "buckets": {},
                                    "sum": None, "count": None})
        if kind == "_bucket":
            if "le" not in labels:
                raise PromParseError(
                    f"{name}: _bucket sample without an 'le' label")
            le = labels["le"]
            if le != "+Inf":
                try:
                    float(le)
                except ValueError:
                    raise PromParseError(
                        f"{name}: bad le value {le!r}") from None
            if le in s["buckets"]:
                raise PromParseError(f"{name}: duplicate le={le!r}")
            s["buckets"][le] = value
        elif kind == "_sum":
            s["sum"] = value
        else:
            s["count"] = value

    out: Dict[str, List[dict]] = {f: [] for f in hist_families}
    for (family, _k), s in series.items():
        ctx = f"{family}{s['labels']}"
        if s["sum"] is None or s["count"] is None:
            raise PromParseError(f"{ctx}: missing _sum or _count sample")
        if "+Inf" not in s["buckets"]:
            raise PromParseError(f"{ctx}: no le=\"+Inf\" bucket")
        if s["buckets"]["+Inf"] != s["count"]:
            raise PromParseError(
                f"{ctx}: +Inf bucket {s['buckets']['+Inf']} != _count "
                f"{s['count']}")
        finite = sorted((float(le), c) for le, c in s["buckets"].items()
                        if le != "+Inf")
        prev = 0.0
        for le, c in finite:
            if c < prev:
                raise PromParseError(
                    f"{ctx}: bucket counts decrease at le={le}")
            prev = c
        if finite and s["buckets"]["+Inf"] < finite[-1][1]:
            raise PromParseError(
                f"{ctx}: +Inf bucket below the largest finite bucket")
        out[family].append(s)
    for family in hist_families:
        if not out[family]:
            raise PromParseError(
                f"{family}: TYPE histogram declared but no samples")
    return out


def parse_exposition(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """Parse exposition text; raises :class:`PromParseError` on any
    malformed line. Returns [(metric_name, labels, value), ...]."""
    samples: List[Tuple[str, Dict[str, str], float]] = []
    for lineno, line in enumerate(text.split("\n"), 1):
        if line == "":
            continue
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) < 3 or parts[0] != "#" \
                    or parts[1] not in ("HELP", "TYPE"):
                raise _err(lineno, "bad comment line", line)
            if not _NAME.match(parts[2]):
                raise _err(lineno, f"bad metric name {parts[2]!r}", line)
            if parts[1] == "TYPE":
                if len(parts) < 4 or parts[3] not in _TYPES:
                    raise _err(lineno, "bad TYPE", line)
            continue
        samples.append(_parse_sample(line, lineno))
    return samples
