"""NetRing transport unit tests: the TCP session layer around the
model-checked protocol (conformance with the spec itself is
test_net_ring_conformance.py). Everything here runs two endpoints in
one process over real authenticated loopback connections."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from ray_tpu.core import net_ring
from ray_tpu.experimental.channel import (
    TAG_BYTES,
    TAG_ERROR,
    TAG_STOP,
    TAG_TENSOR,
    ChannelClosed,
    ChannelTimeout,
)


@pytest.fixture()
def ring_pair():
    made = []

    def make(ring_id, n_slots=4, capacity=1 << 20, **kw):
        reader = net_ring.create_reader(ring_id, n_slots, capacity, **kw)
        host = net_ring.ensure_host()
        writer = net_ring.NetRingWriter.connect(
            host.address, host.authkey, ring_id, n_slots, capacity)
        made.append((writer, reader))
        return writer, reader

    yield make
    for w, r in made:
        w.close()
        r.close()


def test_roundtrip_tags_and_order(ring_pair):
    w, r = ring_pair("t_basic")
    w.write(b"raw", tag=TAG_BYTES, timeout=5)
    w.write(b"err", tag=TAG_ERROR, timeout=5)
    assert r.read(timeout=5) == (TAG_BYTES, b"raw")
    assert r.read(timeout=5) == (TAG_ERROR, b"err")
    # STOP raises ChannelClosed exactly like the shm rings
    w.write(b"", tag=TAG_STOP, timeout=5)
    with pytest.raises(ChannelClosed):
        r.read(timeout=5)


def test_tensor_path_no_serializer(ring_pair):
    from ray_tpu.experimental.channel import STATS

    w, r = ring_pair("t_tensor")
    before = STATS["serialized_bytes"]
    arr = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    w.write_array(arr, timeout=5)
    tag, out = r.read(timeout=5)
    assert tag == TAG_TENSOR
    np.testing.assert_array_equal(out, arr)
    assert out.dtype == arr.dtype
    assert STATS["serialized_bytes"] == before  # pure tensor path


def test_window_backpressure_and_drain(ring_pair):
    w, r = ring_pair("t_window", n_slots=3)
    for i in range(3):
        w.write(b"m%d" % i, tag=TAG_BYTES, timeout=5)
    assert not w.writable() and w.occupancy() == 3
    with pytest.raises(ChannelTimeout):
        w.write(b"overflow", tag=TAG_BYTES, timeout=0.2)
    # draining the reader re-opens the window via cumulative acks
    for i in range(3):
        assert r.read(timeout=5) == (TAG_BYTES, b"m%d" % i)
    w.wait_writable(timeout=5)
    assert w.writable()


def test_capacity_enforced(ring_pair):
    w, _r = ring_pair("t_cap", capacity=64)
    with pytest.raises(ValueError):
        w.write(b"x" * 65, tag=TAG_BYTES, timeout=1)


def test_session_break_recovers_via_retransmit(ring_pair):
    """Severing the TCP session mid-window must lose nothing: the
    writer re-dials and Go-Back-N retransmission re-covers whatever
    was in flight (the writer-restart recovery the spec proves)."""
    w, r = ring_pair("t_break", n_slots=4)
    w.write(b"before", tag=TAG_BYTES, timeout=5)
    assert r.read(timeout=5) == (TAG_BYTES, b"before")
    # sever every live session at the host side
    host = net_ring.ensure_host()
    with host._lock:
        conns = list(host._conns)
    for c in conns:
        c.close()
    # writes during the outage park in the pending window
    w.write(b"during", tag=TAG_BYTES, timeout=5)
    w.write(b"during2", tag=TAG_BYTES, timeout=5)
    assert r.read(timeout=15) == (TAG_BYTES, b"during")
    assert r.read(timeout=15) == (TAG_BYTES, b"during2")
    # acks recovered too: the window fully re-opens
    deadline = time.monotonic() + 10
    while w.acked != w.w and time.monotonic() < deadline:
        time.sleep(0.02)
    assert w.acked == w.w


def test_poison_unparks_blocked_reader(ring_pair):
    w, r = ring_pair("t_poison")
    errs = []

    def blocked_read():
        try:
            r.read(timeout=30)
        except ChannelClosed as e:
            errs.append(e)

    t = threading.Thread(target=blocked_read, daemon=True)
    t.start()
    time.sleep(0.2)
    r.poison()
    t.join(timeout=5)
    assert not t.is_alive() and len(errs) == 1
    w.close()


def test_poison_prefix_targets_dag_uid(ring_pair):
    _w1, r1 = ring_pair("uidA_e0_0")
    _w2, r2 = ring_pair("uidB_e0_0")
    assert net_ring.poison_rings("uidA_") == 1
    with pytest.raises(ChannelClosed):
        r1.read(timeout=1)
    # the other DAG's ring is untouched
    _w2.write(b"ok", tag=TAG_BYTES, timeout=5)
    assert r2.read(timeout=5) == (TAG_BYTES, b"ok")


def test_wait_writable_is_all_or_nothing_safe(ring_pair):
    """A window observed open stays open until the (single) writer
    thread produces — the invariant CompiledDAG.execute's multi-edge
    all-or-nothing input round relies on."""
    w, r = ring_pair("t_wait", n_slots=2)
    w.wait_writable(timeout=5)
    w.write(b"1", tag=TAG_BYTES, timeout=0)  # must not block
    w.wait_writable(timeout=5)
    w.write(b"2", tag=TAG_BYTES, timeout=0)
    assert r.read(timeout=5)[1] == b"1"
    assert r.read(timeout=5)[1] == b"2"


def test_chaos_wire_point_drops_data_then_retransmit_recovers():
    """wire.send.nrd=drop@N loses exactly the N-th data message; the
    retransmit timer must deliver it anyway (end-to-end through the
    real TCP session)."""
    from ray_tpu.core import fault_injection

    reader = net_ring.create_reader("t_chaos_d", 4, 1 << 16)
    host = net_ring.ensure_host()
    w = net_ring.NetRingWriter.connect(host.address, host.authkey,
                                       "t_chaos_d", 4, 1 << 16)
    try:
        fault_injection.configure("wire.send.nrd=drop@2")
        w.write(b"first", tag=TAG_BYTES, timeout=5)
        w.write(b"second", tag=TAG_BYTES, timeout=5)  # dropped on send
        assert reader.read(timeout=10) == (TAG_BYTES, b"first")
        # recovered by Go-Back-N retransmission, not lost
        assert reader.read(timeout=10) == (TAG_BYTES, b"second")
    finally:
        fault_injection.reset()
        w.close()
        reader.close()


def test_tensor_send_writev_zero_copy(ring_pair):
    """The framed tensor body is writev'd segment-by-segment into the
    session socket: NO intermediate joined copy of the tensor exists on
    the send path (the pre-writev code paid one join + one pickle copy
    per tensor). ``STATS["tensor_copy_bytes"]`` counts exactly the
    fallback joins — a real TCP session must not make any."""
    from ray_tpu.experimental.channel import STATS

    w, r = ring_pair("t_writev")
    before_copy = STATS["tensor_copy_bytes"]
    before_tensor = STATS["tensor_bytes"]
    arr = np.arange(512 * 257, dtype=np.float32).reshape(512, 257)
    w.write_array(arr, timeout=5)
    tag, out = r.read(timeout=5)
    assert tag == TAG_TENSOR
    np.testing.assert_array_equal(out, arr)
    # the tensor moved (counter grew by its bytes)...
    assert STATS["tensor_bytes"] - before_tensor == arr.nbytes
    # ...with zero full-tensor copies assembled on the send path
    assert STATS["tensor_copy_bytes"] == before_copy


def test_tensor_segments_retransmit_after_session_break(ring_pair):
    """Segment payloads live in _unacked like any slot: a session break
    before the ack retransmits the SAME segments and the reader still
    reassembles the identical tensor (durable-slot contract holds on
    the zero-copy path)."""
    w, r = ring_pair("t_writev_rt", n_slots=2)
    arr = np.arange(1024, dtype=np.int32)
    w.write_array(arr, timeout=5)
    tag, out = r.read(timeout=5)
    np.testing.assert_array_equal(out, arr)
    # sever every live session at the host side; the write during the
    # outage parks in the unacked window as retained segments
    host = net_ring.ensure_host()
    with host._lock:
        conns = list(host._conns)
    for c in conns:
        c.close()
    arr2 = arr * 3
    w.write_array(arr2, timeout=5)
    tag, out2 = r.read(timeout=15)
    assert tag == TAG_TENSOR
    np.testing.assert_array_equal(out2, arr2)
