"""ray_tpu.tune tests (reference model: python/ray/tune/tests/)."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import (
    AsyncHyperBandScheduler,
    BasicVariantGenerator,
    MedianStoppingRule,
    PopulationBasedTraining,
    Trainable,
    TuneConfig,
    Tuner,
)


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=8, num_tpus=0)
    yield
    ray_tpu.shutdown()


def test_sample_domains():
    rng = np.random.RandomState(0)
    assert 0 <= tune.uniform(0, 1).sample(rng) <= 1
    v = tune.loguniform(1e-4, 1e-1).sample(rng)
    assert 1e-4 <= v <= 1e-1
    assert tune.randint(0, 10).sample(rng) in range(10)
    assert tune.choice(["a", "b"]).sample(rng) in ("a", "b")
    assert tune.quniform(0, 10, 2).sample(rng) % 2 == 0


def test_grid_expansion():
    from ray_tpu.tune.sample import expand_grid

    space = {"a": tune.grid_search([1, 2, 3]),
             "b": tune.grid_search(["x", "y"]), "c": 7}
    variants = expand_grid(space)
    assert len(variants) == 6
    assert all(v["c"] == 7 for v in variants)


def test_function_trainable(ray_init, tmp_path):
    def train_fn(config):
        for i in range(5):
            tune.report({"score": config["x"] * (i + 1),
                         "training_iteration": i + 1})

    results = tune.run(
        train_fn, config={"x": tune.grid_search([1, 2, 3])},
        metric="score", mode="max", storage_path=str(tmp_path))
    assert len(results) == 3
    best = results.get_best_result()
    assert best.config["x"] == 3
    assert best.metrics["score"] == 15


def test_tuner_api(ray_init, tmp_path):
    def train_fn(config):
        tune.report({"loss": (config["lr"] - 0.1) ** 2})

    from ray_tpu.train.config import RunConfig

    tuner = Tuner(
        train_fn,
        param_space={"lr": tune.grid_search([0.01, 0.1, 1.0])},
        tune_config=TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(storage_path=str(tmp_path), name="t"))
    grid = tuner.fit()
    assert grid.get_best_result().config["lr"] == 0.1


def test_num_samples(ray_init, tmp_path):
    def train_fn(config):
        tune.report({"v": config["x"]})

    results = tune.run(train_fn, config={"x": tune.uniform(0, 1)},
                       num_samples=5, metric="v", mode="max",
                       storage_path=str(tmp_path))
    assert len(results) == 5
    xs = [r.config["x"] for r in [results[i] for i in range(5)]]
    assert len(set(xs)) > 1


def test_class_trainable(ray_init, tmp_path):
    class MyTrainable(Trainable):
        def setup(self, config):
            self.x = config["x"]
            self.total = 0

        def step(self):
            self.total += self.x
            return {"total": self.total}

        def save_checkpoint(self, d):
            with open(os.path.join(d, "state.txt"), "w") as f:
                f.write(str(self.total))

        def load_checkpoint(self, d):
            with open(os.path.join(d, "state.txt")) as f:
                self.total = int(f.read())

    results = tune.run(MyTrainable, config={"x": tune.grid_search([1, 5])},
                       stop={"training_iteration": 4},
                       metric="total", mode="max",
                       storage_path=str(tmp_path))
    best = results.get_best_result()
    assert best.metrics["total"] == 20


def test_asha_stops_bad_trials():
    """Deterministic scheduler unit test: interleaved reports, the weak
    trial is culled at a rung while the strong one survives."""
    from ray_tpu.tune.controller import Trial
    from ray_tpu.tune.schedulers import TrialScheduler

    sched = AsyncHyperBandScheduler(
        metric="score", mode="max", time_attr="training_iteration",
        max_t=100, grace_period=2, reduction_factor=2)
    trials = {q: Trial(trial_id=f"t{q}", config={"q": q}, trial_dir="")
              for q in (1, 2, 4, 8)}
    stopped = set()
    for it in range(1, 21):
        # strongest reports first so rung cutoffs are meaningful
        for q in (8, 4, 2, 1):
            if q in stopped:
                continue
            decision = sched.on_trial_result(
                None, trials[q], {"score": q * it,
                                  "training_iteration": it})
            if decision == TrialScheduler.STOP:
                stopped.add(q)
    assert 8 not in stopped
    assert 1 in stopped


def test_median_stopping(ray_init, tmp_path):
    def train_fn(config):
        for i in range(10):
            tune.report({"score": config["q"],
                         "training_iteration": i + 1})

    results = tune.run(
        train_fn, config={"q": tune.grid_search([1, 1, 1, 10])},
        metric="score", mode="max",
        scheduler=MedianStoppingRule(grace_period=2,
                                     min_samples_required=2),
        storage_path=str(tmp_path))
    assert len(results) == 4


def test_experiment_state_saved(ray_init, tmp_path):
    def train_fn(config):
        tune.report({"a": 1})

    tune.run(train_fn, config={}, name="exp1", storage_path=str(tmp_path),
             metric="a", mode="max")
    state = os.path.join(str(tmp_path), "exp1", "experiment_state.json")
    assert os.path.exists(state)


def test_trial_failure_marks_error(ray_init, tmp_path):
    def train_fn(config):
        if config["x"] == 1:
            raise RuntimeError("boom")
        tune.report({"ok": 1})

    results = tune.run(train_fn, config={"x": tune.grid_search([0, 1])},
                       metric="ok", mode="max",
                       storage_path=str(tmp_path))
    assert len(results.errors) == 1
    assert results.get_best_result().config["x"] == 0


def test_with_parameters(ray_init, tmp_path):
    big = np.arange(1000)

    def train_fn(config, data=None):
        tune.report({"s": float(data.sum())})

    results = tune.run(tune.with_parameters(train_fn, data=big),
                       config={}, metric="s", mode="max",
                       storage_path=str(tmp_path))
    assert results.get_best_result().metrics["s"] == float(big.sum())


def test_pbt_runs(ray_init, tmp_path):
    class PBTTrainable(Trainable):
        def setup(self, config):
            self.lr = config["lr"]
            self.score = 0.0

        def step(self):
            self.score += self.lr
            return {"score": self.score}

        def save_checkpoint(self, d):
            with open(os.path.join(d, "s.txt"), "w") as f:
                f.write(str(self.score))

        def load_checkpoint(self, d):
            with open(os.path.join(d, "s.txt")) as f:
                self.score = float(f.read())

    pbt = PopulationBasedTraining(
        time_attr="training_iteration", perturbation_interval=2,
        hyperparam_mutations={"lr": [0.1, 1.0, 10.0]}, seed=0)
    results = tune.run(
        PBTTrainable, config={"lr": tune.choice([0.1, 1.0, 10.0])},
        num_samples=4, stop={"training_iteration": 6},
        metric="score", mode="max", scheduler=pbt,
        storage_path=str(tmp_path), checkpoint_freq=2)
    assert len(results) == 4
    assert results.get_best_result().metrics["score"] > 0


def test_fetch_reads_done_before_draining_queue():
    """Lost-result race regression (the tier-1 tune load flake): the
    trainable thread puts its final report THEN sets _done; fetch must
    therefore read _done BEFORE draining, or a put+done landing
    between the drain and the flag read reports done=True with
    results still queued — the controller stops the trial and the
    final reports (e.g. the best score) are silently dropped.

    Drives the raw actor class (no cluster) with a queue whose
    get_nowait simulates the racing thread: the first drain sees
    nothing, and the moment the drain finishes, the final result and
    the done flag appear.  Order-correct fetch reports done=False for
    that round and picks up the result (with done) next round;
    order-broken fetch loses it."""
    import queue as _q

    from ray_tpu.tune.controller import _FunctionTrainableActor

    raw = _FunctionTrainableActor._cls
    actor = object.__new__(raw)
    actor._error = None
    actor._done = False

    class RacingQueue:
        """Empty until the first full drain completes; then the
        trainable 'thread' publishes its final result and sets done."""

        def __init__(self, owner):
            self.owner = owner
            self.items = []
            self.raced = False

        def get_nowait(self):
            if self.items:
                return self.items.pop(0)
            if not self.raced:
                # the drain just observed "empty": NOW the trainable
                # finishes — final result enqueued, done flag set
                self.raced = True
                self.items.append({"score": 42})
                self.owner._done = True
            raise _q.Empty

    actor._queue = RacingQueue(actor)

    results, done, error = raw.fetch(actor)
    # the done flag was read before the race fired: this round must
    # NOT claim completion (the result arrives with the next round)
    assert done is False, (
        "fetch read _done after draining: the final result would be "
        "dropped when the controller stops the trial on done=True")
    results2, done2, _ = raw.fetch(actor)
    assert done2 is True
    assert (results + results2) == [{"score": 42}]
