"""Multi-agent RLlib (round-4 VERDICT missing #1 / ask #4).

Reference: rllib/env/multi_agent_env_runner.py:55, multi_agent_episode.py,
core/rl_module/multi_rl_module.py. The learning gate trains two
independent policies with PPO on the cooperative SimpleSpread task and
requires a clear joint improvement over the random-policy baseline.
"""

import functools

import numpy as np
import pytest

from ray_tpu.rllib import (MultiAgentEnvRunner, MultiAgentEpisode,
                           MultiRLModuleSpec, PPOConfig, RLModuleSpec,
                           SimpleSpread, map_all_to)


def _env_creator():
    return SimpleSpread(n_agents=2, max_steps=25)


def _two_policy_mapping(aid):
    return {"agent_0": "p0", "agent_1": "p1"}[aid]


class TestMultiAgentEnv:
    def test_dict_api(self):
        env = _env_creator()
        obs, info = env.reset(seed=3)
        assert set(obs) == {"agent_0", "agent_1"}
        assert obs["agent_0"].shape == (8,)
        obs, rew, term, trunc, _ = env.step({"agent_0": 1, "agent_1": 2})
        # cooperative: identical team reward for every agent
        assert rew["agent_0"] == rew["agent_1"] < 0
        assert term["__all__"] is False
        for _ in range(24):
            obs, rew, term, trunc, _ = env.step(
                {"agent_0": 0, "agent_1": 0})
        assert trunc["__all__"] is True
        assert env.agents == []

    def test_reward_improves_when_agents_spread(self):
        env = _env_creator()
        env.reset(seed=0)
        # teleport agents onto the landmarks: reward must be ~0
        env._pos = env._landmarks.copy()
        _, rew, _, _, _ = env.step({"agent_0": 0, "agent_1": 0})
        assert rew["agent_0"] > -1e-3


class TestMultiAgentEpisode:
    def test_per_agent_trajectories_and_global_clock(self):
        ep = MultiAgentEpisode()
        ep.add_reset({"a": np.zeros(2, np.float32),
                      "b": np.ones(2, np.float32)})
        ep.add_step({"a": 1, "b": 0}, {"a": -0.1, "b": -0.2},
                    {"a": 0.5, "b": 0.6},
                    {"a": np.full(2, 2.0, np.float32),
                     "b": np.full(2, 3.0, np.float32)},
                    {"a": 1.0, "b": 2.0}, {"__all__": False},
                    {"__all__": False})
        # agent b sits out step 1 (turn-based envs)
        ep.add_step({"a": 2}, {"a": -0.3}, {"a": 0.7},
                    {"a": np.full(2, 4.0, np.float32)},
                    {"a": 0.5}, {"__all__": True}, {"__all__": False})
        assert ep.is_done
        trajs = ep.agent_trajectories()
        assert len(trajs["a"]["actions"]) == 2
        assert len(trajs["b"]["actions"]) == 1
        assert ep.agent_episodes["a"].env_ts == [0, 1]
        assert ep.agent_episodes["b"].env_ts == [0]
        assert ep.total_reward == pytest.approx(3.5)

    def test_cut_carries_live_state(self):
        ep = MultiAgentEpisode()
        ep.add_reset({"a": np.zeros(2, np.float32)})
        ep.add_step({"a": 1}, {"a": 0.0}, {"a": 0.0},
                    {"a": np.ones(2, np.float32)}, {"a": 0.0},
                    {"__all__": False}, {"__all__": False})
        nxt = ep.cut()
        assert nxt.env_t == 1
        assert set(nxt.pending_obs()) == {"a"}
        # truncated chunk keeps a bootstrap obs
        assert ep.agent_trajectories()["a"]["last_obs"] is not None


class TestMultiAgentEnvRunner:
    def test_sample_shapes_shared_policy(self):
        spec = MultiRLModuleSpec(
            module_specs={"shared": RLModuleSpec(hiddens=(16,))},
            policy_mapping_fn=functools.partial(map_all_to, "shared"))
        runner = MultiAgentEnvRunner(_env_creator, spec, num_envs=2,
                                     rollout_len=30, seed=0)
        weights = {mid: m.init(__import__("jax").random.PRNGKey(0))
                   for mid, m in runner.modules.items()}
        batch, stats = runner.sample(weights)
        assert set(batch) == {"shared"}
        # 2 envs x 25-step episodes inside a 30-step rollout: both agents'
        # rows land in the shared module's trajectory list
        total = sum(len(t["actions"]) for t in batch["shared"])
        assert total == stats["agent_steps"] > 0
        assert stats["env_steps"] == 60
        for t in batch["shared"]:
            assert t["obs"].shape[1] == 8
            assert t["vf_last"] == 0.0 or not t["terminated"]

    def test_sample_routes_per_policy(self):
        spec = MultiRLModuleSpec(
            module_specs={"p0": RLModuleSpec(hiddens=(16,)),
                          "p1": RLModuleSpec(hiddens=(16,))},
            policy_mapping_fn=_two_policy_mapping)
        runner = MultiAgentEnvRunner(_env_creator, spec, num_envs=2,
                                     rollout_len=25, seed=0)
        import jax

        weights = {mid: m.init(jax.random.PRNGKey(i))
                   for i, (mid, m) in enumerate(runner.modules.items())}
        batch, stats = runner.sample(weights)
        assert set(batch) == {"p0", "p1"}
        n0 = sum(len(t["actions"]) for t in batch["p0"])
        n1 = sum(len(t["actions"]) for t in batch["p1"])
        assert n0 == n1  # simultaneous env: equal participation


def _random_baseline(n_episodes=40):
    env = _env_creator()
    rng = np.random.default_rng(0)
    returns = []
    for i in range(n_episodes):
        env.reset(seed=100 + i)
        total = 0.0
        done = False
        while not done:
            _, rew, term, trunc, _ = env.step(
                {a: int(rng.integers(0, 5)) for a in env.possible_agents})
            total += sum(rew.values())
            done = term["__all__"] or trunc["__all__"]
        returns.append(total)
    return float(np.mean(returns))


class TestMultiAgentLearningGate:
    @pytest.mark.slow  # >5s on the 1-core box: full-tier only (tier-1 wall budget)
    def test_two_policies_learn_simple_spread(self):
        """Two independent PPO policies must jointly beat the random
        baseline by a wide margin (reference:
        check_learning_achieved-style gate on an MPE cooperative task)."""
        baseline = _random_baseline()
        config = (PPOConfig()
                  .environment(env_creator=_env_creator)
                  .env_runners(num_env_runners=0,
                               num_envs_per_env_runner=8,
                               rollout_fragment_length=50)
                  .training(lr=1e-3, gamma=0.95, train_batch_size=800,
                            minibatch_size=256, num_epochs=6,
                            entropy_coeff=0.01)
                  .multi_agent(policies={"p0": RLModuleSpec(hiddens=(64, 64)),
                                         "p1": RLModuleSpec(hiddens=(64, 64))},
                               policy_mapping_fn=_two_policy_mapping)
                  .debugging(seed=0))
        algo = config.build()
        best = -np.inf
        for _ in range(250):
            r = algo.train()
            best = max(best, r.get("episode_return_mean", -np.inf))
            if best >= baseline * 0.55:  # returns are negative
                break
        algo.cleanup()
        # random ~= -77; the tuned run reaches ~-18 (sweep: gamma 0.95
        # is the lever on 25-step episodes), so 0.55x baseline (~-42)
        # demonstrates joint learning with wide margin and stops early
        assert best >= baseline * 0.55, (
            f"multi-agent PPO failed to learn: best={best:.1f} "
            f"baseline={baseline:.1f}")
