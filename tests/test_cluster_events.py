"""Structured cluster event log: emit -> buffer -> GCS ring -> state API /
dashboard / JSONL. Reference: the GCS cluster-event table behind
``ray list cluster-events`` + the export-event pipeline.
"""

import json
import os
import time
import urllib.request

import ray_tpu
from ray_tpu.util import events, state


# --------------------------------------------------------------- unit


class TestEventBuffer:
    def test_emit_without_sink_parks_bounded(self):
        buf = events._EventBuffer(maxlen=3)
        for i in range(5):
            buf.emit(events.ClusterEvent(
                ts=float(i), severity="INFO", source="T", entity_id="",
                message=f"m{i}"))
        assert len(buf._buf) == 3  # bounded pre-sink
        got = []
        buf.set_sink(got.extend)
        assert [e["message"] for e in got] == ["m2", "m3", "m4"]
        buf.clear_sink()

    def test_sink_failure_reparks_and_retries(self):
        buf = events._EventBuffer()
        calls = []

        def flaky(batch):
            calls.append(list(batch))
            if len(calls) == 1:
                raise ConnectionError("link down")

        buf.set_sink(flaky, flush_interval_s=0.05)
        buf.emit(events.ClusterEvent(ts=0.0, severity="INFO", source="T",
                                     entity_id="", message="x"))
        deadline = time.monotonic() + 5
        while len(calls) < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(calls) >= 2
        assert calls[1][0]["message"] == "x"  # re-delivered after failure
        buf.clear_sink()

    def test_clear_sink_requires_match(self):
        buf = events._EventBuffer()
        sink = lambda b: None  # noqa: E731
        buf.set_sink(sink)
        buf.clear_sink(lambda b: None)  # different sink: no-op
        assert buf._sink is not None
        buf.clear_sink(sink)
        assert buf._sink is None

    def test_event_log_writer_rotates_at_size_cap(self, tmp_path):
        w = events.EventLogWriter(str(tmp_path), max_bytes=400)
        for i in range(20):
            w.write([{"ts": float(i), "severity": "INFO", "source": "T",
                      "entity_id": "", "message": "x" * 40, "attrs": {}}])
        w.close()
        main = tmp_path / "logs" / "events" / "events.jsonl"
        rotated = tmp_path / "logs" / "events" / "events.jsonl.1"
        assert rotated.exists()  # rotated at the cap
        assert main.stat().st_size < 500  # current file stays bounded
        # rotated + current together never exceed ~2x the cap
        assert main.stat().st_size + rotated.stat().st_size < 1200

    def test_filter_events(self):
        rows = [
            {"severity": "INFO", "source": "NODE", "message": "a"},
            {"severity": "WARNING", "source": "SCHEDULER", "message": "b"},
            {"severity": "ERROR", "source": "NODE", "message": "c"},
        ]
        assert [r["message"] for r in
                events.filter_events(rows, severity="warning")] == ["b"]
        assert [r["message"] for r in
                events.filter_events(rows, min_severity="WARNING")] == \
            ["b", "c"]
        assert [r["message"] for r in
                events.filter_events(rows, source="node")] == ["a", "c"]
        assert [r["message"] for r in events.filter_events(
            rows, source="NODE", min_severity="ERROR")] == ["c"]


# --------------------------------------------------------------- e2e


class _FakeProvider:
    """Records create/terminate calls without launching real daemons."""

    def __init__(self):
        self.nodes = []
        self.created = 0

    def create_node(self, node_config):
        pid = f"fake-{self.created}"
        self.created += 1
        self.nodes.append(pid)
        return pid

    def terminate_node(self, pid):
        if pid in self.nodes:
            self.nodes.remove(pid)

    def non_terminated_nodes(self):
        return list(self.nodes)

    def shutdown(self):
        self.nodes.clear()


def _wait_for(predicate, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = predicate()
        if got:
            return got
        time.sleep(0.25)
    return predicate()


def test_cluster_events_end_to_end():
    """Events from >= 5 distinct subsystems (node lifecycle, scheduler,
    autoscaler, serve, tune) land in one severity-filterable log, are
    served over /api/events, and persist as JSONL."""
    from ray_tpu import serve, tune
    from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig
    from ray_tpu.core import api
    from ray_tpu.dashboard import start_dashboard

    ray_tpu.init(num_cpus=4, num_tpus=0)
    dash = None
    scaler = None
    try:
        head = api._get_head()

        # NODE: init already emitted node-alive; add/remove one for dead
        extra = head.add_node({"CPU": 1})
        head.remove_node(extra.hex)

        # SCHEDULER: an ask no node shape can ever fit
        @ray_tpu.remote(num_cpus=64)
        def impossible():
            return 1

        impossible.remote()  # never completes; infeasible event instead
        assert _wait_for(lambda: state.list_cluster_events(
            source="SCHEDULER", severity="WARNING"))

        # AUTOSCALER: the pending infeasible ask is feasible on the
        # provider's (bigger) node shape -> a launch decision
        provider = _FakeProvider()
        scaler = Autoscaler(head, provider, AutoscalerConfig(
            min_workers=0, max_workers=2, interval_s=9999,
            node_config={"num_cpus": 128}))
        scaler.update()
        assert provider.created >= 1
        assert state.list_cluster_events(source="AUTOSCALER")

        # SERVE: deploy -> controller (a worker actor) emits over the
        # worker channel
        @serve.deployment
        def hello(x):
            return "hi"

        serve.run(hello.bind(), route_prefix=None)
        assert _wait_for(lambda: state.list_cluster_events(source="SERVE"))

        # TUNE: one tiny trial -> RUNNING + TERMINATED transitions
        def train_fn(config):
            tune.report({"score": config["x"]})

        tune.run(train_fn, config={"x": 1}, metric="score", mode="max",
                 storage_path=os.path.join(head.session_dir, "tune"))
        tune_events = _wait_for(
            lambda: state.list_cluster_events(source="TUNE"))
        assert any(e["attrs"].get("state") == "RUNNING"
                   for e in tune_events)
        assert any(e["attrs"].get("state") == "TERMINATED"
                   for e in tune_events)

        rows = state.list_cluster_events()
        sources = {e["source"] for e in rows}
        assert {"NODE", "SCHEDULER", "AUTOSCALER", "SERVE",
                "TUNE"} <= sources
        # severity filtering
        warnings = state.list_cluster_events(severity="WARNING")
        assert warnings and all(e["severity"] == "WARNING"
                                for e in warnings)
        assert any(e["source"] == "NODE" and "dead" in e["message"]
                   for e in warnings)
        floor = state.list_cluster_events(min_severity="WARNING")
        assert all(e["severity"] in ("WARNING", "ERROR") for e in floor)
        assert len(floor) >= len(warnings)

        # dashboard endpoint with filters
        dash = start_dashboard(port=0, with_jobs=False)
        base = f"http://127.0.0.1:{dash.address[1]}"
        with urllib.request.urlopen(
                base + "/api/events?source=NODE", timeout=10) as r:
            via_http = json.loads(r.read())
        assert via_http and all(e["source"] == "NODE" for e in via_http)
        with urllib.request.urlopen(
                base + "/api/events?min_severity=WARNING&limit=5",
                timeout=10) as r:
            capped = json.loads(r.read())
        assert len(capped) <= 5
        assert all(e["severity"] in ("WARNING", "ERROR") for e in capped)

        # JSONL persistence under session_dir/logs/events/
        events.flush()
        path = os.path.join(head.session_dir, "logs", "events",
                            "events.jsonl")
        assert os.path.isfile(path)
        with open(path) as f:
            persisted = [json.loads(line) for line in f]
        assert {"NODE", "SCHEDULER", "AUTOSCALER"} <= \
            {e["source"] for e in persisted}
        assert all({"ts", "severity", "source", "entity_id", "message",
                    "attrs"} <= set(e) for e in persisted)
    finally:
        if dash is not None:
            dash.stop()
        serve.shutdown()
        if scaler is not None:
            scaler.stop(terminate_nodes=True)
        ray_tpu.shutdown()


def test_event_log_disabled(monkeypatch):
    from ray_tpu.core.config import global_config

    monkeypatch.setattr(global_config(), "event_log_enabled", False)
    before = len(events._buffer._buf)
    events.emit("INFO", "TEST", "should be dropped")
    assert len(events._buffer._buf) == before


def test_worker_emitted_events_reach_head(ray_start_regular):
    """emit() inside a task rides the worker channel to the head ring."""
    @ray_tpu.remote
    def noisy():
        from ray_tpu.util import events as ev

        ev.emit("WARNING", "USERCODE", "worker-side event",
                entity_id="w1", detail=42)
        return 1

    assert ray_tpu.get(noisy.remote()) == 1
    got = _wait_for(lambda: state.list_cluster_events(source="USERCODE"))
    assert got and got[-1]["message"] == "worker-side event"
    assert got[-1]["attrs"]["detail"] == 42
    assert got[-1]["severity"] == "WARNING"
