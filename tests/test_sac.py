"""SAC (continuous control) + the standalone replay-buffer family.

Round-3 VERDICT item 4: the off-policy/continuous corner of the algorithm
space (reference: rllib/algorithms/sac/sac.py:524,
utils/replay_buffers/prioritized_episode_buffer.py).
"""

import numpy as np
import pytest

from ray_tpu.rllib.replay_buffers import (PrioritizedReplayBuffer,
                                          ReplayBuffer, SumTree)
from ray_tpu.rllib.sac import SAC, SACConfig


class TestReplayBuffers:
    def test_uniform_ring_wraps(self):
        buf = ReplayBuffer(100)
        buf.add({"obs": np.arange(250, dtype=np.float32).reshape(250, 1),
                 "actions": np.arange(250)})
        assert len(buf) == 100
        s = buf.sample(64, np.random.default_rng(0))
        assert (s["actions"] >= 150).all()  # only the newest survive

    def test_sumtree_proportional(self):
        t = SumTree(16)
        t.set(np.arange(16), np.ones(16))
        t.set(np.array([5]), np.array([9.0]))
        assert abs(t.total - 24.0) < 1e-9
        found = t.find_prefix(np.random.rand(8000) * t.total)
        frac5 = (found == 5).mean()
        assert 0.25 < frac5 < 0.5  # 9/24 = 0.375 expected

    def test_per_reprioritization(self):
        rng = np.random.default_rng(0)
        p = PrioritizedReplayBuffer(128)
        p.add({"obs": np.arange(64, dtype=np.float32).reshape(64, 1),
               "actions": np.arange(64)})
        s = p.sample(8, rng)
        assert s["weights"].max() <= 1.0 + 1e-6
        boosted = s["indices"]
        p.update_priorities(boosted, np.full(len(boosted), 50.0))
        s2 = p.sample(1000, rng)
        assert np.isin(s2["indices"], boosted).mean() > 0.5


class TestSAC:
    def test_sac_mechanics(self):
        cfg = (SACConfig()
               .environment("Pendulum-v1")
               .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                            rollout_fragment_length=32)
               .training(train_batch_size=128, learning_starts=200,
                         updates_per_iteration=4, batch_size=64)
               .debugging(seed=0))
        algo = cfg.build()
        r1 = algo.train()
        r2 = algo.train()
        algo.cleanup()
        assert r2["buffer_size"] > r1["buffer_size"]
        assert r2["learner"], "no learner stats after learning_starts"
        assert np.isfinite(r2["learner"]["critic_loss"])
        # entropy temperature is being adapted
        assert r2["learner"]["alpha"] != 1.0

    def test_sac_prioritized_replay(self):
        cfg = (SACConfig()
               .environment("Pendulum-v1")
               .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                            rollout_fragment_length=16)
               .training(train_batch_size=64, learning_starts=64,
                         updates_per_iteration=4, batch_size=32,
                         prioritized_replay=True)
               .debugging(seed=0))
        algo = cfg.build()
        r = algo.train()
        r = algo.train()
        algo.cleanup()
        assert np.isfinite(r["learner"]["critic_loss"])

    def test_sac_checkpoint_roundtrip(self, tmp_path):
        cfg = (SACConfig()
               .environment("Pendulum-v1")
               .env_runners(num_env_runners=0, num_envs_per_env_runner=2,
                            rollout_fragment_length=8)
               .training(train_batch_size=16, learning_starts=16,
                         updates_per_iteration=2, batch_size=8))
        algo = cfg.build()
        algo.train()
        algo.save_checkpoint(str(tmp_path))
        w0 = algo.learner_group.get_weights()
        algo.cleanup()

        algo2 = SAC.from_checkpoint(str(tmp_path), cfg.copy())
        w1 = algo2.learner_group.get_weights()
        algo2.cleanup()
        for a, b in zip(np.asarray(list(w0.values()), dtype=object),
                        np.asarray(list(w1.values()), dtype=object)):
            np.testing.assert_allclose(a, b)


@pytest.mark.slow  # ~50s of env steps + gradient work on a 1-core box;
# the appo/impala/dqn/bc learning gates keep RL covered in tier-1
def test_sac_learns_pendulum():
    """Learning gate: mean return rises from ~-1300 (random) to >= -600
    on Pendulum-v1 (reference: tuned_examples/sac/pendulum-sac.yaml
    solves at ~-150; -600 proves strong learning within CI budget)."""
    cfg = (SACConfig()
           .environment("Pendulum-v1")
           .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                        rollout_fragment_length=32)
           .training(train_batch_size=128, learning_starts=1000,
                     updates_per_iteration=128, batch_size=128,
                     actor_lr=1e-3, critic_lr=1e-3, alpha_lr=1e-3)
           .debugging(seed=0))
    algo = cfg.build()
    best = -1e9
    for i in range(120):
        r = algo.train()
        ret = r.get("episode_return_mean")
        if ret is not None:
            best = max(best, ret)
        if best >= -500:
            break
    algo.cleanup()
    # round-4 tightening (round-3 audit: -900 "would pass a badly-tuned
    # implementation"): convergence to the -500 early-exit lands well
    # inside the CI budget on this contended box, so -600 is safe margin
    assert best >= -600, f"SAC failed to learn Pendulum: best={best}"
