"""ML-ingest datasource round-trips: images, TFRecords, WebDataset
(VERDICT round-3 ask #7; reference: ray.data read_images/read_tfrecords/
read_webdataset, _internal/datasource/image_datasource.py:29)."""

import os

import numpy as np
import pytest

import ray_tpu
import ray_tpu.data as rd

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def _make_images(root, classes=("cat", "dog"), per_class=3, size=(8, 10)):
    rng = np.random.default_rng(0)
    paths = []
    for cls in classes:
        d = os.path.join(root, cls)
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            arr = rng.integers(0, 255, (size[0], size[1], 3), dtype=np.uint8)
            p = os.path.join(d, f"{cls}_{i}.png")
            Image.fromarray(arr).save(p)
            paths.append(p)
    return paths


def test_read_images_folder_with_labels(cluster, tmp_path):
    _make_images(str(tmp_path))
    ds = rd.read_images(str(tmp_path), labels="dirname", include_paths=True)
    rows = ds.take_all()
    assert len(rows) == 6
    labels = sorted({r["label"] for r in rows})
    assert labels == ["cat", "dog"]
    assert rows[0]["image"].shape == (8, 10, 3)
    assert rows[0]["image"].dtype == np.uint8


def test_read_images_mixed_sizes_without_resize(cluster, tmp_path):
    # different image sizes across blocks: combining ops must fall back
    # to row blocks instead of crashing on tensor-schema mismatch
    _make_images(str(tmp_path / "a"), classes=("x",), per_class=2,
                 size=(4, 4))
    _make_images(str(tmp_path / "b"), classes=("y",), per_class=2,
                 size=(5, 7))
    rows = rd.read_images(str(tmp_path)).take_all()
    shapes = sorted({r["image"].shape for r in rows})
    assert shapes == [(4, 4, 3), (5, 7, 3)]


def test_read_images_resize_batches_stack(cluster, tmp_path):
    _make_images(str(tmp_path), per_class=2)
    ds = rd.read_images(str(tmp_path), size=(16, 16))
    batch = ds.take_batch(4, batch_format="numpy")
    assert batch["image"].shape == (4, 16, 16, 3)


def test_tfrecords_roundtrip(cluster, tmp_path):
    rows = [
        {"name": f"row{i}", "score": float(i) / 3.0, "count": i,
         "vec": np.arange(4, dtype=np.float32) + i,
         "ids": np.asarray([i, i * 2, -i], np.int64)}
        for i in range(20)
    ]
    path = str(tmp_path / "tfr")
    rd.from_items(rows).write_tfrecords(path)
    files = os.listdir(path)
    assert files and all(f.endswith(".tfrecords") for f in files)

    back = rd.read_tfrecords(path).take_all()
    assert len(back) == 20
    by_count = {int(r["count"]): r for r in back}
    for i in range(20):
        r = by_count[i]
        assert r["name"] == b"row%d" % i or r["name"] == f"row{i}".encode()
        assert abs(float(r["score"]) - i / 3.0) < 1e-6
        np.testing.assert_allclose(np.asarray(r["vec"]),
                                   np.arange(4, dtype=np.float32) + i)
        assert list(np.asarray(r["ids"])) == [i, i * 2, -i]


@pytest.mark.slow  # >5s on the 1-core box: full-tier only (tier-1 wall budget)
def test_tfrecords_wire_compatible_with_tensorflow(cluster, tmp_path):
    """Our dependency-free codec must parse records written by TF itself
    (and vice versa) — proof of wire-format compatibility."""
    tf = pytest.importorskip("tensorflow")
    path = str(tmp_path / "tf_native")
    os.makedirs(path)
    fpath = os.path.join(path, "native.tfrecords")
    with tf.io.TFRecordWriter(fpath) as w:
        for i in range(5):
            ex = tf.train.Example(features=tf.train.Features(feature={
                "x": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[i, i + 1])),
                "y": tf.train.Feature(
                    float_list=tf.train.FloatList(value=[i * 0.5])),
                "s": tf.train.Feature(
                    bytes_list=tf.train.BytesList(value=[b"v%d" % i])),
            }))
            w.write(ex.SerializeToString())
    rows = rd.read_tfrecords(path).take_all()
    assert len(rows) == 5
    rows.sort(key=lambda r: float(r["y"]))
    assert list(np.asarray(rows[2]["x"])) == [2, 3]
    assert rows[3]["s"] == b"v3"

    # reverse direction: TF parses OUR records
    ours = str(tmp_path / "ours")
    rd.from_items([{"a": 7, "b": b"hello"}]).write_tfrecords(ours)
    fname = os.path.join(ours, os.listdir(ours)[0])
    recs = list(tf.data.TFRecordDataset([fname]))
    ex = tf.train.Example.FromString(recs[0].numpy())
    assert ex.features.feature["a"].int64_list.value[0] == 7
    assert ex.features.feature["b"].bytes_list.value[0] == b"hello"


def test_webdataset_roundtrip(cluster, tmp_path):
    rng = np.random.default_rng(1)
    rows = [
        {"__key__": f"{i:04d}",
         "jpg": rng.integers(0, 255, (6, 6, 3), dtype=np.uint8),
         "cls": i % 3,
         "txt": f"caption {i}",
         "emb.npy": rng.normal(size=4).astype(np.float32)}
        for i in range(12)
    ]
    # encode images as real JPEG bytes for the jpg column
    import io as _io

    for r in rows:
        buf = _io.BytesIO()
        Image.fromarray(r["jpg"]).save(buf, format="PNG")
        r["jpg"] = buf.getvalue()

    path = str(tmp_path / "wds")
    rd.from_items(rows).write_webdataset(path, rows_per_shard=5)
    shards = [f for f in os.listdir(path) if f.endswith(".tar")]
    assert len(shards) >= 3  # 12 rows / 5 per shard (per write task)

    back = rd.read_webdataset(path).take_all()
    assert len(back) == 12
    back.sort(key=lambda r: r["__key__"])
    assert back[0]["__key__"] == "0000"
    assert back[0]["cls"] == 0
    assert back[0]["txt"] == "caption 0"
    assert back[0]["jpg"].shape == (6, 6, 3)
    np.testing.assert_allclose(back[3]["emb.npy"],
                               np.asarray([r for r in rows
                                           if r["__key__"] == "0003"
                                           ][0]["emb.npy"]))


def _write_delta_table(root):
    """Hand-build a Delta table: 3 commits incl. a remove + a checkpoint."""
    import json as _json

    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(os.path.join(root, "_delta_log"))

    def data_file(name, ids):
        pq.write_table(pa.table({"id": ids}), os.path.join(root, name))

    def commit(v, actions):
        with open(os.path.join(root, "_delta_log",
                               f"{v:020d}.json"), "w") as f:
            for a in actions:
                f.write(_json.dumps(a) + "\n")

    data_file("part-0.parquet", [1, 2])
    data_file("part-1.parquet", [3, 4])
    commit(0, [{"metaData": {"id": "t"}},
               {"add": {"path": "part-0.parquet"}},
               {"add": {"path": "part-1.parquet"}}])
    # commit 1: compact part-0+part-1 into part-2
    data_file("part-2.parquet", [1, 2, 3, 4, 5])
    commit(1, [{"remove": {"path": "part-0.parquet"}},
               {"remove": {"path": "part-1.parquet"}},
               {"add": {"path": "part-2.parquet"}}])
    data_file("part-3.parquet", [6])
    commit(2, [{"add": {"path": "part-3.parquet"}}])


def test_read_delta_log_replay_and_time_travel(cluster, tmp_path):
    table = str(tmp_path / "delta")
    _write_delta_table(table)
    # latest: compacted file + the new add (removed files excluded)
    rows = sorted(r["id"] for r in rd.read_delta(table).take_all())
    assert rows == [1, 2, 3, 4, 5, 6]
    # time travel to version 0: the original two files
    rows0 = sorted(r["id"] for r in
                   rd.read_delta(table, version=0).take_all())
    assert rows0 == [1, 2, 3, 4]


def test_read_delta_checkpoint(cluster, tmp_path):
    import json as _json

    import pyarrow as pa
    import pyarrow.parquet as pq

    table = str(tmp_path / "delta_ck")
    _write_delta_table(table)
    # checkpoint at version 1 (lists the state after the compaction)
    ck = pa.table({
        "add": [{"path": "part-2.parquet"}, None, None],
        "remove": [None, {"path": "part-0.parquet"},
                   {"path": "part-1.parquet"}],
    })
    pq.write_table(ck, os.path.join(
        table, "_delta_log", f"{1:020d}.checkpoint.parquet"))
    with open(os.path.join(table, "_delta_log", "_last_checkpoint"),
              "w") as f:
        f.write(_json.dumps({"version": 1}))
    # replay = checkpoint state + commit 2 only
    rows = sorted(r["id"] for r in rd.read_delta(table).take_all())
    assert rows == [1, 2, 3, 4, 5, 6]


def test_read_delta_partition_columns_and_empty(cluster, tmp_path):
    import json as _json

    import pyarrow as pa
    import pyarrow.parquet as pq

    table = str(tmp_path / "delta_part")
    os.makedirs(os.path.join(table, "_delta_log"))
    os.makedirs(os.path.join(table, "date=2024-01-01"))
    pq.write_table(pa.table({"x": [1, 2]}),
                   os.path.join(table, "date=2024-01-01", "p0.parquet"))
    with open(os.path.join(table, "_delta_log",
                           f"{0:020d}.json"), "w") as f:
        f.write(_json.dumps({"add": {
            "path": "date%3D2024-01-01/p0.parquet",  # url-encoded path
            "partitionValues": {"date": "2024-01-01"}}}) + "\n")
    # hmm: percent-encoding of '=' — decoded path must resolve
    os.rename(os.path.join(table, "date=2024-01-01"),
              os.path.join(table, "date=2024-01-01"))
    rows = rd.read_delta(table).take_all()
    assert sorted(r["x"] for r in rows) == [1, 2]
    # partition column attached from the log (not in the file)
    assert all(r["date"] == "2024-01-01" for r in rows)
    # column selection including the partition column works
    rows2 = rd.read_delta(table, columns=["date", "x"]).take_all()
    assert rows2[0]["date"] == "2024-01-01"

    # empty table (all files removed) -> empty dataset, not an error
    with open(os.path.join(table, "_delta_log",
                           f"{1:020d}.json"), "w") as f:
        f.write(_json.dumps({"remove": {
            "path": "date%3D2024-01-01/p0.parquet"}}) + "\n")
    assert rd.read_delta(table).take_all() == []
