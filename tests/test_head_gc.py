"""Head record GC + honest wait(fetch_local) (round-4 ask #4; reference:
GcsTaskManager capped task storage, ray.wait fetch_local semantics)."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core import runtime as runtime_mod


def _head():
    return runtime_mod.get_current_runtime().head


class TestRecordGC:
    def setup_method(self):
        ray_tpu.init(num_cpus=2)

    def teardown_method(self):
        ray_tpu.shutdown()

    def test_settled_head_records_fold_away(self):
        # num_cpus=2 forces the head path (direct grants 1 worker slot)
        @ray_tpu.remote(num_cpus=2)
        def f(i):
            return i

        refs = [f.remote(i) for i in range(10)]
        assert ray_tpu.get(refs) == list(range(10))
        head = _head()
        assert len(head.tasks) == 10
        # refs still held: lineage keeps every record
        assert head.gc_task_records(ttl_s=0) == 0
        assert len(head.tasks) == 10
        del refs
        import gc as _gc

        from ray_tpu.core.object_ref import flush_pending_drops

        # ref releases drain through the __del__ reaper thread: wait on
        # the observable record drop with a deadline (same load-flake
        # family as test_head_path_stream_records_released)
        dropped = 0
        deadline = time.monotonic() + 10
        while dropped < 10 and time.monotonic() < deadline:
            _gc.collect()
            flush_pending_drops(timeout=2.0)
            dropped += head.gc_task_records(ttl_s=0)
            if dropped < 10:
                time.sleep(0.05)
        assert dropped == 10
        assert len(head.tasks) == 0

    def test_live_actor_creation_record_survives(self):
        @ray_tpu.remote
        class A:
            def ping(self):
                return "ok"

        a = A.remote()
        assert ray_tpu.get(a.ping.remote()) == "ok"
        head = _head()
        assert head.gc_task_records(ttl_s=0) == 0  # live incarnation
        assert len(head.tasks) == 1
        ray_tpu.kill(a)
        time.sleep(0.5)
        assert head.gc_task_records(ttl_s=0) == 1
        assert len(head.tasks) == 0
        assert a._actor_id not in head.actors  # dead actor record folded

    def test_stream_records_and_pins_released(self):
        @ray_tpu.remote(num_returns="streaming")
        def gen(n):
            for i in range(n):
                yield i

        g = gen.remote(5)
        tid = g._task_id
        out = [ray_tpu.get(r) for r in g]
        assert out == [0, 1, 2, 3, 4]
        head = _head()
        # direct-path streams never create head stream records (items
        # ride the direct reply chain to the owner)
        assert not head.streams
        # owner-side buffer purges when the generator handle is released
        rt = runtime_mod.get_current_runtime()
        assert tid in rt.direct._streams
        del g
        import gc

        gc.collect()
        deadline = time.monotonic() + 5
        while tid in rt.direct._streams and time.monotonic() < deadline:
            time.sleep(0.05)
        assert tid not in rt.direct._streams

    def test_head_path_stream_records_released(self):
        # num_cpus=2 forces the head path: the head stream-record
        # protocol (records + pins) must still GC
        @ray_tpu.remote(num_returns="streaming", num_cpus=2)
        def gen(n):
            for i in range(n):
                yield i

        out = [ray_tpu.get(r) for r in gen.remote(5)]
        assert out == [0, 1, 2, 3, 4]
        head = _head()
        assert head.streams
        # The item/primary ObjectRefs release through the __del__ reaper
        # thread, and GC only folds the record once their pins drop —
        # wait on that observable release with a deadline instead of
        # expecting one sweep to win the race (seed flake: reaper timing)
        import gc as _gc

        from ray_tpu.core.object_ref import flush_pending_drops

        deadline = time.monotonic() + 10
        while head.streams and time.monotonic() < deadline:
            _gc.collect()
            flush_pending_drops(timeout=2.0)
            head.gc_task_records(ttl_s=0)
            if head.streams:
                time.sleep(0.05)
        assert not head.streams

    def test_bounded_under_sustained_load(self):
        """Many head-path tasks with a tiny TTL: records stay bounded."""
        @ray_tpu.remote(num_cpus=2)
        def unit(i):
            return i

        head = _head()
        for batch in range(5):
            refs = [unit.remote(i) for i in range(20)]
            ray_tpu.get(refs)
            del refs
            import gc as _gc

            _gc.collect()
            head.gc_task_records(ttl_s=0)
        assert len(head.tasks) == 0


class TestFetchLocal:
    def test_wait_fetch_local_pulls_from_daemon(self):
        cluster = Cluster(head_node_args={"num_cpus": 1})
        cluster.add_node(num_cpus=2, resources={"far": 1},
                         separate_process=True)
        try:
            import numpy as np

            @ray_tpu.remote(resources={"far": 0.1})
            def make():
                return np.ones(200_000, dtype=np.int64)  # >1 MB, remote

            ref = make.remote()
            # fetch_local=False: ready as soon as it exists remotely,
            # without a local copy
            ready, _ = ray_tpu.wait([ref], timeout=120, fetch_local=False)
            assert ready
            head = _head()
            assert not head.head_node.store.contains(ref.id)
            # fetch_local=True: the wait itself pulls the bytes down
            ready, _ = ray_tpu.wait([ref], timeout=120, fetch_local=True)
            assert ready
            deadline = time.monotonic() + 30
            while (not head.head_node.store.contains(ref.id)
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            assert head.head_node.store.contains(ref.id)
        finally:
            cluster.shutdown()
