"""Serve deployment graphs (round-4 ask #6; reference:
python/ray/serve/dag.py + _private/deployment_graph_build.py)."""

import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve import InputNode


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@serve.deployment
class Doubler:
    def apply(self, x):
        return x * 2


@serve.deployment
class Adder:
    def __init__(self, bias=0):
        self.bias = bias

    def apply(self, x):
        return x + self.bias


@serve.deployment
class Combiner:
    def combine(self, a, b):
        return {"sum": a + b}


def test_two_stage_graph(cluster):
    with InputNode() as inp:
        doubled = Doubler.bind().apply.bind(inp)
        out = Adder.bind(10).apply.bind(doubled)
    handle = serve.run(out, route_prefix=None)
    assert handle.remote(5).result(timeout=60) == 20  # 5*2 + 10
    assert handle.remote(0).result(timeout=60) == 10
    # both stages exist as first-class deployments
    st = serve.status()
    assert "Doubler" in st and "Adder" in st and "DAGDriver" in st


def test_diamond_graph_branches(cluster):
    with InputNode() as inp:
        left = Doubler.bind().apply.bind(inp)
        right = Adder.bind(100).apply.bind(inp)
        out = Combiner.bind().combine.bind(left, right)
    handle = serve.run(out, route_prefix=None)
    assert handle.remote(3).result(timeout=60) == {"sum": 6 + 103}


def test_rolling_update_of_one_stage_under_traffic(cluster):
    """Redeploying one stage (new version/bias) swaps replicas under
    live traffic via the long-poll handles; no request fails."""
    with InputNode() as inp:
        out = Adder.options(num_replicas=2).bind(1).apply.bind(inp)
    handle = serve.run(out, route_prefix=None)
    assert handle.remote(1).result(timeout=60) == 2

    failures = []
    seen = set()
    stop = threading.Event()

    def traffic():
        while not stop.is_set():
            try:
                seen.add(handle.remote(1).result(timeout=30))
            except Exception as e:  # noqa: BLE001
                failures.append(e)
            time.sleep(0.02)

    t = threading.Thread(target=traffic)
    t.start()
    try:
        time.sleep(0.5)
        # roll the stage to bias=5 (a new code version)
        with InputNode() as inp:
            out2 = Adder.options(num_replicas=2, version="2").bind(
                5).apply.bind(inp)
        serve.run(out2, route_prefix=None)
        deadline = time.time() + 60
        while time.time() < deadline and 6 not in seen:
            time.sleep(0.1)
    finally:
        stop.set()
        t.join()
    assert not failures, failures[:3]
    assert 2 in seen and 6 in seen  # old then new version served
