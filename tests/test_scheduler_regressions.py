"""Regression tests for resource-accounting and cancellation bugs found in
review of the initial core runtime."""

import time

import pytest

import ray_tpu
from ray_tpu.util import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


def test_failed_actor_creation_releases_resources(ray_start_regular):
    @ray_tpu.remote(num_cpus=3)
    class Broken:
        def __init__(self):
            raise ValueError("nope")

        def ping(self):
            return 1

    for _ in range(3):  # would exhaust 4 CPUs if creations leaked
        b = Broken.remote()
        with pytest.raises(ray_tpu.RayTpuError):
            ray_tpu.get(b.ping.remote(), timeout=60)
    deadline = time.time() + 10
    while time.time() < deadline:
        if ray_tpu.available_resources().get("CPU", 0) == 4.0:
            break
        time.sleep(0.2)
    assert ray_tpu.available_resources().get("CPU", 0) == 4.0


def test_wait_returns_exactly_num_returns(ray_start_regular):
    @ray_tpu.remote
    def f(i):
        return i

    refs = [f.remote(i) for i in range(4)]
    ray_tpu.get(refs)  # all sealed
    ready, not_ready = ray_tpu.wait(refs, num_returns=1)
    assert len(ready) == 1
    assert len(not_ready) == 3


def test_cancel_queued_task_never_runs(ray_start_regular, tmp_path):
    marker = tmp_path / "ran"

    @ray_tpu.remote(num_cpus=4)
    def hog():
        time.sleep(2)

    @ray_tpu.remote(num_cpus=4)
    def side_effect(path):
        open(path, "w").close()
        return True

    h = hog.remote()  # occupies all CPUs so next task queues
    ref = side_effect.remote(str(marker))
    ray_tpu.cancel(ref)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    ray_tpu.get(h)
    time.sleep(1.0)
    assert not marker.exists(), "cancelled task still executed"


def test_remove_pg_with_running_tasks_no_double_credit(ray_start_regular):
    pg = placement_group([{"CPU": 2}], strategy="PACK")
    assert pg.ready(timeout=30)

    @ray_tpu.remote(num_cpus=2)
    def slow():
        time.sleep(2)
        return 1

    strat = PlacementGroupSchedulingStrategy(placement_group=pg,
                                             placement_group_bundle_index=0)
    ref = slow.options(scheduling_strategy=strat).remote()
    time.sleep(1.0)  # task is running inside the bundle
    remove_placement_group(pg)
    ray_tpu.get(ref, timeout=60)
    time.sleep(0.5)
    avail = ray_tpu.available_resources()
    assert avail.get("CPU", 0) <= 4.0, f"over-credited: {avail}"
    deadline = time.time() + 10
    while time.time() < deadline and avail.get("CPU", 0) != 4.0:
        time.sleep(0.2)
        avail = ray_tpu.available_resources()
    assert avail.get("CPU", 0) == 4.0


def test_default_actor_holds_zero_cpus_alive(ray_start_regular):
    """Reference semantics: a default actor needs 1 CPU to schedule but
    holds 0 while alive — live actors must not starve plain tasks."""
    @ray_tpu.remote
    class A:
        def m(self):
            return 1

    actors = [A.remote() for _ in range(4)]  # as many as cluster CPUs
    ray_tpu.get([a.m.remote() for a in actors])
    assert ray_tpu.available_resources().get("CPU", 0) == 4.0
    # plain tasks schedule fine with all 4 actors alive
    f = ray_tpu.remote(lambda x: x * 2)
    assert sorted(ray_tpu.get([f.remote(i) for i in range(4)],
                              timeout=60)) == [0, 2, 4, 6]


def test_explicit_actor_cpus_held_and_released_on_kill(ray_start_regular):
    @ray_tpu.remote(num_cpus=2)
    class B:
        def m(self):
            return 1

    b = B.remote()
    ray_tpu.get(b.m.remote())
    assert ray_tpu.available_resources().get("CPU", 0) == 2.0
    ray_tpu.kill(b)
    deadline = time.time() + 15
    while time.time() < deadline:
        if ray_tpu.available_resources().get("CPU", 0) == 4.0:
            break
        time.sleep(0.2)
    # killed actor's lifetime reservation came back (this leaked before)
    assert ray_tpu.available_resources().get("CPU", 0) == 4.0
