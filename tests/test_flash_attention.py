"""Flash-attention kernel correctness vs the exact reference path.

Runs the Pallas kernels in interpreter mode on the CPU test mesh (shapes
kept tiny — interpret mode executes block-by-block in Python). The same
kernels run compiled on real TPU via bench.py / the flagship model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.flash_attention import flash_attention
from ray_tpu.parallel.ring_attention import plain_attention


def _ref(q, k, v, causal=True):
    Hq, Hkv = q.shape[2], k.shape[2]
    if Hq != Hkv:
        rep = Hq // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return plain_attention(q, k, v, causal=causal)


CASES = [
    # (B, T, Hq, Hkv, D, causal) — T must block (>=64); D=64 exercises the
    # lane-padding path, Hq != Hkv the GQA index map.
    (1, 128, 2, 1, 64, True),
    (1, 128, 2, 2, 128, False),
]


@pytest.mark.parametrize("B,T,Hq,Hkv,D,causal", CASES)
def test_forward_matches_reference(B, T, Hq, Hkv, D, causal):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, T, Hq, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, Hkv, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, Hkv, D), jnp.float32)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    expect = _ref(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               atol=2e-2, rtol=2e-2)


def test_grads_match_reference():
    B, T, Hq, Hkv, D, causal = 1, 128, 2, 1, 64, True
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(B, T, Hq, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, Hkv, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, Hkv, D), jnp.float32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, interpret=True)
        return (o * o).sum()

    def loss_ref(q, k, v):
        o = _ref(q, k, v, causal)
        return (o * o).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-2, rtol=5e-2, err_msg=name)


def test_fallback_on_odd_shapes():
    # T=100 doesn't block: must silently use the exact path
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(1, 100, 2, 32), jnp.float32)
    k = jnp.asarray(rng.randn(1, 100, 2, 32), jnp.float32)
    v = jnp.asarray(rng.randn(1, 100, 2, 32), jnp.float32)
    out = flash_attention(q, k, v, causal=True)
    expect = _ref(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)
