"""Multi-node cluster tests (reference model: python/ray/tests/test_multi_node.py,
test_placement_group.py, test_object_spilling.py)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)


def test_multi_node_scheduling(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2, resources={"special": 1})

    @ray_tpu.remote(resources={"special": 1})
    def on_special():
        return ray_tpu.get_runtime_context().get_node_id()

    @ray_tpu.remote
    def anywhere():
        return ray_tpu.get_runtime_context().get_node_id()

    special_node = ray_tpu.get(on_special.remote(), timeout=60)
    assert special_node is not None
    assert ray_tpu.cluster_resources()["CPU"] == 4.0


def test_node_affinity(ray_start_cluster):
    cluster = ray_start_cluster
    n2 = cluster.add_node(num_cpus=2)

    @ray_tpu.remote
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    strat = NodeAffinitySchedulingStrategy(node_id=n2.hex, soft=False)
    assert ray_tpu.get(where.options(scheduling_strategy=strat).remote(),
                       timeout=60) == n2.hex


def test_spread_strategy(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)

    @ray_tpu.remote(num_cpus=1)
    def where():
        time.sleep(0.2)
        return ray_tpu.get_runtime_context().get_node_id()

    refs = [where.options(scheduling_strategy="SPREAD").remote() for _ in range(6)]
    nodes = set(ray_tpu.get(refs, timeout=60))
    assert len(nodes) >= 2


def test_placement_group_pack(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=4)
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=30)

    @ray_tpu.remote(num_cpus=1)
    def in_pg():
        return ray_tpu.get_runtime_context().get_node_id()

    strat = PlacementGroupSchedulingStrategy(placement_group=pg,
                                             placement_group_bundle_index=0)
    n = ray_tpu.get(in_pg.options(scheduling_strategy=strat).remote(), timeout=60)
    assert n is not None
    remove_placement_group(pg)


def test_placement_group_strict_spread(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    pg = placement_group([{"CPU": 1}] * 3, strategy="STRICT_SPREAD")
    assert pg.ready(timeout=30)
    st = pg.state()
    assert len(set(st["bundle_nodes"])) == 3


def test_placement_group_infeasible_until_node_added(ray_start_cluster):
    cluster = ray_start_cluster
    pg = placement_group([{"CPU": 8}], strategy="PACK")
    assert not pg.ready(timeout=0.5)
    cluster.add_node(num_cpus=8)
    assert pg.ready(timeout=30)


def test_actor_on_specific_resources(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2, resources={"accel": 4})

    @ray_tpu.remote(resources={"accel": 2})
    class A:
        def where(self):
            return ray_tpu.get_runtime_context().get_node_id()

    a = A.remote()
    assert ray_tpu.get(a.where.remote(), timeout=60)
    # two such actors consume all 4 "accel" units
    b = A.remote()
    assert ray_tpu.get(b.where.remote(), timeout=60)
    avail = ray_tpu.available_resources()
    assert avail.get("accel", 0) == 0


def test_object_transfer_between_nodes(ray_start_cluster):
    cluster = ray_start_cluster
    n2 = cluster.add_node(num_cpus=2, resources={"there": 1})

    @ray_tpu.remote(resources={"there": 1})
    def produce():
        return np.full((300_000,), 3.0)

    @ray_tpu.remote(num_cpus=1)
    def consume(x):
        return float(x.sum())

    data = produce.remote()
    assert ray_tpu.get(consume.remote(data), timeout=60) == 900_000.0


def test_node_death_task_retry(ray_start_cluster):
    cluster = ray_start_cluster
    doomed = cluster.add_node(num_cpus=2, resources={"doomed": 2})

    @ray_tpu.remote(max_retries=2, num_cpus=1)
    def slow_task():
        time.sleep(3)
        return ray_tpu.get_runtime_context().get_node_id()

    # prefer the doomed node via affinity(soft) so the first attempt lands there
    strat = NodeAffinitySchedulingStrategy(node_id=doomed.hex, soft=True)
    ref = slow_task.options(scheduling_strategy=strat).remote()
    time.sleep(1.0)
    cluster.remove_node(doomed)
    result = ray_tpu.get(ref, timeout=90)
    assert result != doomed.hex


def test_actor_restart_on_node_death(ray_start_cluster):
    cluster = ray_start_cluster
    doomed = cluster.add_node(num_cpus=2, resources={"spot": 1})

    @ray_tpu.remote(max_restarts=1, resources={"spot": 1})
    class Pinned:
        def ping(self):
            return ray_tpu.get_runtime_context().get_node_id()

    # restartable actor needs the resource available elsewhere after death
    backup = cluster.add_node(num_cpus=2, resources={"spot": 1})
    a = Pinned.remote()
    first = ray_tpu.get(a.ping.remote(), timeout=60)
    cluster.remove_node(doomed if first == doomed.hex else backup)
    deadline = time.time() + 60
    second = None
    while time.time() < deadline:
        try:
            second = ray_tpu.get(a.ping.remote(), timeout=10)
            break
        except ray_tpu.RayTpuError:
            time.sleep(0.5)
    assert second is not None and second != first
