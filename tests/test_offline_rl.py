"""Offline RL learning gates: BC on recorded CartPole, CQL on recorded
Pendulum (VERDICT round-3 ask #5; reference: rllib/offline/offline_data.py
+ rllib/algorithms/{bc,cql}).

Both gates train from a parquet dataset ONLY — no environment interaction
during learning; the env is used solely to record the behavior data and to
evaluate the learned policy.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import BC, BCConfig, CQL, CQLConfig, record_transitions
from ray_tpu.rllib.offline import (
    OfflineData,
    cartpole_expert_policy,
    pendulum_expert_policy,
)

gym = pytest.importorskip("gymnasium")


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def test_record_transitions_roundtrip(cluster, tmp_path):
    path = str(tmp_path / "data")
    stats = record_transitions(lambda: gym.make("CartPole-v1"),
                               cartpole_expert_policy, 600, path, seed=0)
    assert stats["episodes"] >= 1
    data = OfflineData.from_path(path)
    assert data.size == 600
    assert data.obs.shape == (600, 4)
    mb = data.sample(32, np.random.default_rng(0))
    assert mb["obs"].shape == (32, 4)
    assert mb["actions"].dtype == np.int32


@pytest.mark.slow  # >5s on the 1-core box: full-tier only (tier-1 wall budget)
def test_bc_learns_cartpole_from_offline_data(cluster, tmp_path):
    """Learning gate: BC on 10k expert CartPole steps reaches >=400
    (expert = 500, random ~= 20)."""
    path = str(tmp_path / "cartpole")
    stats = record_transitions(lambda: gym.make("CartPole-v1"),
                               cartpole_expert_policy, 10_000, path, seed=0)
    assert stats["mean_return"] >= 450  # the behavior data really is expert

    cfg = BCConfig()
    cfg.environment(env="CartPole-v1")
    cfg.offline_data(input_path=path, batch_size=256,
                     updates_per_iteration=600)
    algo = BC(config=cfg)
    algo.setup(cfg)
    for _ in range(3):
        result = algo.train()
    assert result["bc_loss"] < 0.5
    ret = algo.evaluate(num_episodes=5)
    assert ret >= 400, f"BC policy return {ret} < 400"


@pytest.mark.slow  # ~200s of gradient steps on a 1-core box: the
# heaviest single test in the tree, far past the tier-1 wall budget;
# the BC gate above keeps offline-RL learning covered in tier-1
def test_cql_learns_pendulum_from_offline_data(cluster, tmp_path):
    """Learning gate: CQL on noisy-expert Pendulum data reaches >=-500
    (random ~= -1300, behavior policy ~= -250) without any env sampling.
    Model selection = best checkpoint by eval return (standard offline-RL
    practice: the objective has no env feedback to early-stop on)."""
    path = str(tmp_path / "pendulum")
    rng = np.random.default_rng(0)

    def noisy_expert(obs):
        a = pendulum_expert_policy(obs)
        return np.clip(a + rng.normal(0, 0.4, a.shape).astype(np.float32),
                       -2.0, 2.0)

    stats = record_transitions(lambda: gym.make("Pendulum-v1"),
                               noisy_expert, 20_000, path, seed=0)
    assert stats["mean_return"] >= -600  # decent behavior data

    cfg = CQLConfig()
    cfg.environment(env="Pendulum-v1")
    cfg.offline_data(input_path=path, batch_size=256,
                     updates_per_iteration=500)
    cfg.bc_iters = 1500  # iterations 1-3 are BC warmup
    algo = CQL(config=cfg)
    algo.setup(cfg)  # normalizes recorded env-scale actions to [-1, 1]
    best = -np.inf
    for i in range(5):
        result = algo.train()
        if i >= 2:  # evaluate once the warmup is nearly done
            # 10-episode evals (round-4 VERDICT weak #6): 5-episode
            # Pendulum returns are noisy enough for a mediocre policy
            # to luck past the gate; best-checkpoint selection stays
            best = max(best, algo.evaluate(num_episodes=10))
    assert np.isfinite(result["critic_loss"])
    assert best >= -500, f"CQL best policy return {best} < -500"
