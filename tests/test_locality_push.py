"""Locality-aware dispatch + push/broadcast object plane (round-4 ask #3;
reference: lease_policy.h:56 LocalityAwareLeasePolicy,
object_manager/push_manager.h:30, the '1 GiB broadcast to 50+ nodes'
scalability-envelope row)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core import runtime as runtime_mod


def _head():
    return runtime_mod.get_current_runtime().head


class TestLocalityDispatch:
    def test_direct_consumer_lands_on_block_holder(self):
        """A direct task consuming a large object executes on the node
        holding it instead of shipping the bytes (in-process peers)."""
        cluster = Cluster(head_node_args={"num_cpus": 2})
        n2 = cluster.add_node(num_cpus=2, resources={"holder": 1})
        try:
            @ray_tpu.remote(resources={"holder": 0.1})
            def make():
                return np.ones(300_000, dtype=np.int64)  # 2.4 MB on n2

            @ray_tpu.remote
            def consume(a):
                return (int(a[0]),
                        ray_tpu.get_runtime_context().get_node_id())

            block = make.remote()
            ray_tpu.wait([block], timeout=60, fetch_local=False)
            results = ray_tpu.get(
                [consume.remote(block) for _ in range(4)], timeout=120)
            values = {v for v, _ in results}
            nodes = {n for _, n in results}
            assert values == {1}
            assert nodes == {n2.hex}, f"consumers ran on {nodes}"
            assert len(_head().tasks) == 1  # only make's head record
        finally:
            cluster.shutdown()

    def test_head_path_scheduler_prefers_holder(self):
        """Head-path tasks (num_cpus=2) get a soft locality preference."""
        cluster = Cluster(head_node_args={"num_cpus": 2})
        n2 = cluster.add_node(num_cpus=2, resources={"holder": 1})
        try:
            @ray_tpu.remote(resources={"holder": 0.1})
            def make():
                return np.ones(300_000, dtype=np.int64)

            @ray_tpu.remote(num_cpus=2)
            def consume(a):
                return ray_tpu.get_runtime_context().get_node_id()

            block = make.remote()
            ray_tpu.wait([block], timeout=60, fetch_local=False)
            # the result seals (waking the wait) BEFORE the producer's
            # resources release; wait for settle so n2 is feasible again
            head = _head()
            deadline = time.time() + 30
            while time.time() < deadline:
                rec = head.tasks.get(block.id.task_id())
                if rec is not None and rec.state == "FINISHED":
                    break
                time.sleep(0.02)
            time.sleep(0.2)
            where = ray_tpu.get(consume.remote(block), timeout=120)
            assert where == n2.hex
        finally:
            cluster.shutdown()


class TestPushBroadcast:
    def test_broadcast_tree_reaches_all_daemons(self):
        cluster = Cluster(head_node_args={"num_cpus": 1})
        daemons = [cluster.add_node(num_cpus=1, separate_process=True)
                   for _ in range(4)]
        try:
            head = _head()
            payload = np.random.default_rng(0).integers(
                0, 255, 5_000_000, dtype=np.uint8)  # 5 MB

            # ---- serial baseline: each daemon pulls one by one ----------
            from ray_tpu.util.scheduling_strategies import (
                NodeAffinitySchedulingStrategy,
            )

            serial_ref = ray_tpu.put(payload)
            t0 = time.monotonic()
            for d in daemons:
                @ray_tpu.remote(scheduling_strategy=(
                    NodeAffinitySchedulingStrategy(d.hex, soft=False)))
                def touch(a):
                    return int(a[0])

                assert ray_tpu.get(touch.remote(serial_ref),
                                   timeout=120) == int(payload[0])
            serial_dt = time.monotonic() - t0

            # ---- tree broadcast ----------------------------------------
            bcast_ref = ray_tpu.put(payload + 1)
            t0 = time.monotonic()
            n = head.broadcast_object(bcast_ref.id)
            assert n == 4
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                locs = head.gcs.get_object_locations(bcast_ref.id)
                if len(locs) >= 5:  # head + 4 daemons
                    break
                time.sleep(0.02)
            bcast_dt = time.monotonic() - t0
            locs = head.gcs.get_object_locations(bcast_ref.id)
            assert len(locs) >= 5, f"broadcast reached only {len(locs)}"
            print(f"\nserial pulls: {serial_dt:.2f}s, "
                  f"tree broadcast: {bcast_dt:.2f}s")
            # the tree must not be slower than the serialized pulls
            # (on one machine bandwidth is shared, so parity is the floor;
            # on a real network the tree wins by ~log(n)/n)
            assert bcast_dt < serial_dt * 1.5
        finally:
            cluster.shutdown()
