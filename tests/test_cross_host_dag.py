"""Cross-host compiled graphs: net-ring edges resolved from actor
placement. Daemons here are separate OS processes joined over TCP — the
full multi-host path; an edge between the driver and a daemon-hosted
actor (or between actors on different daemons) must ride a NetRing,
while co-located edges stay /dev/shm, transparently to the caller."""

from __future__ import annotations

import os
import signal
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.exceptions import ActorDiedError
from ray_tpu.core.net_ring import NetRingReader, NetRingWriter
from ray_tpu.dag import InputNode
from ray_tpu.experimental.channel import ShmChannel


def wait_for(cond, timeout=60.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture()
def two_daemons():
    cluster = Cluster(head_node_args={"num_cpus": 1})
    n1 = cluster.add_node(num_cpus=2, resources={"d1": 4},
                          separate_process=True)
    n2 = cluster.add_node(num_cpus=2, resources={"d2": 4},
                          separate_process=True)
    yield cluster, n1, n2
    cluster.shutdown()


@ray_tpu.remote(resources={"d1": 1})
class OnD1:
    def inc(self, x):
        return x + 1

    def pid(self):
        return os.getpid()

    def matmul(self, x):
        import jax.numpy as jnp

        return jnp.asarray(x) @ jnp.asarray(x).T

    def chan_stats(self):
        from ray_tpu.experimental.channel import STATS

        return dict(STATS)


@ray_tpu.remote(resources={"d2": 1})
class OnD2:
    def double(self, x):
        return x * 2

    def rowsum(self, m):
        import jax.numpy as jnp

        return jnp.asarray(m).sum(axis=1)

    def chan_stats(self):
        from ray_tpu.experimental.channel import STATS

        return dict(STATS)


def test_cross_daemon_edges_are_net_rings(two_daemons):
    """driver->d1->d2->driver: every edge crosses a process on a
    different node, so the compile must lay NetRings end to end — and
    the DAG must behave exactly like a shm one (ordering, overlap,
    backpressure)."""
    a, b = OnD1.remote(), OnD2.remote()
    with InputNode() as inp:
        out = b.double.bind(a.inc.bind(inp))
    dag = out.experimental_compile(max_inflight=4)
    try:
        # topology proof: the driver's endpoints are net, not shm
        assert all(isinstance(ch, NetRingWriter)
                   for ch in dag._input_chans), dag._input_chans
        assert isinstance(dag._out, NetRingReader)
        assert not any(isinstance(ch, ShmChannel) for ch in dag._channels)
        for i in range(6):
            assert dag.execute(i).get(timeout=60) == (i + 1) * 2
        # pipelined: max_inflight rounds overlap in flight
        refs = [dag.execute(i) for i in range(4)]
        assert [r.get(timeout=60) for r in refs] == \
            [(i + 1) * 2 for i in range(4)]
    finally:
        dag.teardown()


def test_mixed_topology_shm_and_net(two_daemons):
    """An actor on the HEAD node keeps /dev/shm edges to the driver
    while the daemon-hosted stage gets net rings — per-edge resolution,
    one graph."""

    @ray_tpu.remote  # no resource constraint: lands on the head node
    class Local:
        def triple(self, x):
            return x * 3

    loc, far = Local.remote(), OnD1.remote()
    with InputNode() as inp:
        out = far.inc.bind(loc.triple.bind(inp))
    dag = out.experimental_compile(max_inflight=2)
    try:
        # driver->local edge is shm; local->far and far->driver are net
        assert any(isinstance(ch, ShmChannel) for ch in dag._input_chans)
        assert isinstance(dag._out, NetRingReader)
        for i in range(5):
            assert dag.execute(i).get(timeout=60) == i * 3 + 1
    finally:
        dag.teardown()


def test_tensor_path_crosses_daemons_without_serializer(two_daemons):
    """device_channels=True across daemons: activations ride the
    TAG_TENSOR payload format over the net session — the serializer
    stays at zero bytes on every stage."""
    a, b = OnD1.remote(), OnD2.remote()
    with InputNode() as inp:
        out = b.rowsum.bind(a.matmul.bind(inp))
    dag = out.experimental_compile(buffer_size_bytes=8 << 20,
                                   device_channels=True, max_inflight=2)
    try:
        x = np.arange(64 * 32, dtype=np.float32).reshape(64, 32)
        got = dag.execute(x).get(timeout=120)
        np.testing.assert_allclose(np.asarray(got), (x @ x.T).sum(axis=1),
                                   rtol=1e-4)
        sa = ray_tpu.get(a.chan_stats.remote())
        sb = ray_tpu.get(b.chan_stats.remote())
        assert sa["tensor_bytes"] >= 64 * 64 * 4
        assert sa["serialized_bytes"] == 0, sa
        assert sb["serialized_bytes"] == 0, sb
    finally:
        dag.teardown()


def test_executor_death_cross_daemon_fails_attributed(two_daemons):
    """Killing a daemon-hosted executor worker mid-flight must surface
    as an attributed ActorDiedError on the driver — parked net reads
    unwedge via the poison broadcast, never a bare timeout."""
    a = OnD1.remote()
    pid = ray_tpu.get(a.pid.remote(), timeout=60)
    with InputNode() as inp:
        out = a.inc.bind(inp)
    dag = out.experimental_compile(max_inflight=2)
    assert dag.execute(1).get(timeout=60) == 2
    ref = dag.execute(2)
    os.kill(pid, signal.SIGKILL)
    with pytest.raises(ActorDiedError):
        ref.get(timeout=60)
    dag.teardown()  # bounded, no wedge


def test_rebind_rebuilds_net_edges_to_actors_new_node(two_daemons):
    """THE PR-12 gap this PR closes: after an executor restart the
    rebind must re-resolve placement and rebuild net-ring edges to the
    actor's NEW node — not just re-uid the old shm paths. Kill the
    daemon hosting the actor; failover restarts it on the OTHER daemon;
    the next execute() must dial rings there and produce correct
    results."""
    cluster, n1, n2 = two_daemons

    @ray_tpu.remote(resources={"pool": 1}, max_restarts=2)
    class Movable:
        def inc(self, x):
            return x + 1

    # two daemons share the "pool" resource so failover has a target
    cluster.add_node(num_cpus=1, resources={"pool": 1},
                     separate_process=True)
    cluster.add_node(num_cpus=1, resources={"pool": 1},
                     separate_process=True)
    s = Movable.remote()
    assert ray_tpu.get(s.inc.remote(0), timeout=60) == 1
    from ray_tpu.core.runtime import get_current_runtime

    head = get_current_runtime().head
    loc0 = head.actor_location(s._actor_id)["node_hex"]
    with InputNode() as inp:
        out = s.inc.bind(inp)
    dag = out.experimental_compile(max_inflight=2)
    assert dag.execute(1).get(timeout=60) == 2
    assert isinstance(dag._out, NetRingReader)
    # kill the HOSTING DAEMON (not just the worker): the restart must
    # land on the other pool node
    victim = head.nodes[loc0]
    os.kill(victim.pid, signal.SIGKILL)
    wait_for(lambda: (head.actor_location(s._actor_id) or {})
             .get("node_hex") not in (None, loc0),
             timeout=90, msg="actor failover to the surviving node")
    wait_for(lambda: (head.actor_location(s._actor_id) or {})
             .get("state") == "ALIVE",
             timeout=90, msg="restarted actor alive")
    loc1 = head.actor_location(s._actor_id)["node_hex"]
    assert loc1 != loc0
    # drive the DAG until the rebind lands on the new incarnation
    deadline = time.monotonic() + 90
    value = None
    while time.monotonic() < deadline:
        try:
            value = dag.execute(9, timeout=20).get(timeout=30)
            break
        except Exception:
            time.sleep(0.3)
    assert value == 10, f"rebind to the new node never served: {value!r}"
    # and the rebuilt output edge is a fresh net ring (new uid)
    assert isinstance(dag._out, NetRingReader)
    assert dag._uid in dag._out.ring_id
    dag.teardown()
