"""End-to-end Serve request observability: request ids, access logs,
stage histograms, slow-request events, span trees, status aggregates.

Reference model: serve's request-context + metrics tests
(python/ray/serve/tests/test_metrics.py) over this repo's pipeline:
proxy -> handle -> replica instrumentation (serve/observability.py)
flowing into the standard registry, the cluster event log, and the
tracing pubsub channel.
"""

import json
import os
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core import api
from ray_tpu.util import state, tracing
from ray_tpu.util.metrics import registry, render_prometheus

PORT = 18341


@pytest.fixture
def serve_instance(monkeypatch):
    from ray_tpu.core.config import global_config

    # replica metrics must land on the head fast enough to assert on
    # (the config snapshot ships to workers at init)
    monkeypatch.setattr(global_config(), "metrics_report_interval_ms", 300)
    ray_tpu.init(num_cpus=4, num_tpus=0)
    serve.start(serve.HTTPOptions(port=PORT))
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _get(path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{PORT}{path}", timeout=30) as r:
        return r.status, r.read().decode(), dict(r.headers)


def _access_log_lines():
    d = os.path.join(api._get_head().session_dir, "logs", "serve")
    if not os.path.isdir(d):
        return []
    out = []
    for name in os.listdir(d):
        with open(os.path.join(d, name)) as f:
            out.extend(json.loads(ln) for ln in f if ln.strip())
    return out


def _merged_latency_count(deployment):
    from ray_tpu.serve import observability as obs
    from ray_tpu.util.metrics import aggregate_histogram

    obs.drain_deferred()  # settle the driver's queued records
    total = 0
    for tags, v in aggregate_histogram(
            "ray_tpu_serve_request_latency_seconds").items():
        if dict(tags).get("deployment") == deployment:
            total += v["count"]
    return total


def test_http_requests_yield_ids_logs_histograms_and_spans(serve_instance):
    """The acceptance path: N HTTP requests produce N unique request ids
    (echoed in the x-request-id header), N access-log JSONL lines, e2e
    histogram _count == N, and a joined span tree proxy -> handle ->
    replica for any one request."""
    @serve.deployment
    class Greeter:
        def __call__(self, request):
            return {"hello": serve.get_request_id()}

    serve.run(Greeter.bind(), route_prefix="/greet")
    # DELTA-based histogram count: the driver-process registry outlives
    # clusters, so a same-named deployment in an earlier test file
    # (test_serve.py's Greeter) leaves counts behind — the exact shape
    # of the serve-area tier-1 "load flake" from the PR-13 run (full
    # suite ordering, passes in isolation)
    base_count = _merged_latency_count("Greeter")
    N = 8
    header_ids, body_ids = [], []
    for _ in range(N):
        status, body, headers = _get("/greet")
        assert status == 200
        header_ids.append(headers.get("x-request-id"))
        body_ids.append(json.loads(body)["hello"])
    # ingress-assigned ids: unique, echoed in the response header, and
    # visible to user code via serve.get_request_id()
    assert len(set(header_ids)) == N
    assert header_ids == body_ids

    # one access-log line per request, request ids joined
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        lines = [l for l in _access_log_lines()
                 if l["deployment"] == "Greeter"]
        if len(lines) >= N:
            break
        time.sleep(0.1)
    assert len(lines) == N
    assert {l["request_id"] for l in lines} == set(header_ids)
    for l in lines:
        assert l["status"] == "ok" and l["route"] == "/greet"
        assert l["replica"].startswith("Greeter#")
        assert "exec_ms" in l["timings_ms"]
        assert "replica_queue_wait_ms" in l["timings_ms"]

    # e2e histogram (recorded proxy-side, head process): _count == N
    assert _merged_latency_count("Greeter") - base_count == N

    # replica-side stage histograms flush over the worker channel
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        text = render_prometheus(registry())
        if "ray_tpu_serve_exec_seconds_count" in text \
                and "ray_tpu_serve_replica_queue_wait_seconds" in text:
            break
        time.sleep(0.2)
    from prom_parser import parse_exposition, parse_histograms

    parse_exposition(text)  # every line conformant
    hists = parse_histograms(text)  # strict histogram-family validation
    for fam in ("ray_tpu_serve_request_latency_seconds",
                "ray_tpu_serve_handle_queue_wait_seconds",
                "ray_tpu_serve_replica_queue_wait_seconds",
                "ray_tpu_serve_exec_seconds"):
        assert fam in hists and hists[fam], fam

    # span tree: the root span carries the request id; the handle span
    # parents under it and the replica task span under the handle span
    rid = header_ids[0]
    spans = tracing.get_spans(timeout=10)
    mine = [s for s in spans
            if (s.get("attrs") or {}).get("request_id") == rid]
    assert mine, "no spans tagged with the request id"
    trace_id = mine[0]["trace_id"]
    tree = [s for s in spans if s["trace_id"] == trace_id]
    by_id = {s["span_id"]: s for s in tree}
    root = next(s for s in tree if s["parent_id"] is None)
    assert root["name"].startswith("serve.http")
    handle_span = next(s for s in tree
                       if s["name"] == "serve.handle.Greeter")
    assert handle_span["parent_id"] == root["span_id"]
    replica_span = next(s for s in tree
                        if "handle_request" in s["name"])
    assert by_id[replica_span["parent_id"]] is handle_span


def test_slow_request_emits_warning_event_with_stages(serve_instance):
    @serve.deployment(slow_request_threshold_s=0.05)
    class Sleepy:
        def __call__(self, request):
            time.sleep(0.25)
            return "done"

    serve.run(Sleepy.bind(), route_prefix="/sleepy")
    status, _, headers = _get("/sleepy")
    assert status == 200
    rid = headers.get("x-request-id")

    deadline = time.monotonic() + 10
    slow = []
    while time.monotonic() < deadline and not slow:
        evs = state.list_cluster_events(source="SERVE",
                                        min_severity="WARNING")
        slow = [e for e in evs
                if e["attrs"].get("request_id") == rid]
        time.sleep(0.1)
    assert slow, "no slow-request WARNING event"
    ev = slow[0]
    assert ev["severity"] == "WARNING"
    assert ev["entity_id"] == "Sleepy"
    assert ev["attrs"]["e2e_ms"] >= 250
    stages = ev["attrs"]["stages"]
    assert stages["exec_ms"] >= 200
    assert "replica_queue_wait_ms" in stages
    assert "handle_queue_wait_ms" in stages


def test_errors_and_status_aggregates(serve_instance):
    @serve.deployment
    class Flaky:
        def __call__(self, request):
            if request.query_params.get("boom"):
                raise ValueError("boom")
            return "ok"

    serve.run(Flaky.bind(), route_prefix="/flaky")
    for _ in range(4):
        assert _get("/flaky")[0] == 200
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get("/flaky?boom=1")
    assert ei.value.code == 500

    st = serve.status()["Flaky"]
    assert st["requests"] == 5
    assert st["errors"] == 1
    assert st["error_rate"] == pytest.approx(0.2)
    assert st["latency_ms"]["p50"] is not None
    assert st["latency_ms"]["p99"] is not None

    # error requests get access-log lines with status=error too
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        lines = [l for l in _access_log_lines()
                 if l["deployment"] == "Flaky"
                 and l["status"] == "error"]
        if lines:
            break
        time.sleep(0.1)
    assert lines


def test_polling_result_timeout_not_recorded_as_error(serve_instance):
    """result() is future-like and re-callable: a caller polling with
    short timeouts must not pin the request as an error — the timeout
    signal counts once, and the eventual success records ok."""
    @serve.deployment
    class Slowish:
        def __call__(self, x):
            time.sleep(0.8)
            return "done"

    handle = serve.run(Slowish.bind(), route_prefix=None)
    r = handle.remote(None)
    timeouts = 0
    deadline = time.monotonic() + 30
    while True:
        try:
            v = r.result(timeout=0.1)
            break
        except TimeoutError:
            timeouts += 1
            assert time.monotonic() < deadline
    assert v == "done" and timeouts >= 1
    st = serve.status()["Slowish"]
    assert st["errors"] == 0
    assert st["requests"] == 1
    assert st["timeouts"] == 1  # once, however many polls timed out
    assert st["error_rate"] == 0.0


def test_batching_records_wait_and_size(serve_instance):
    @serve.deployment
    class Batcher:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        async def go(self, xs):
            return [x * 10 for x in xs]

        async def __call__(self, x):
            return await self.go(x)

    handle = serve.run(Batcher.bind(), route_prefix=None)
    rs = [handle.remote(i) for i in range(8)]
    assert sorted(r.result() for r in rs) == [i * 10 for i in range(8)]

    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        text = render_prometheus(registry())
        if "ray_tpu_serve_batch_wait_seconds_count" in text:
            break
        time.sleep(0.2)
    assert "ray_tpu_serve_batch_wait_seconds" in text
    assert "ray_tpu_serve_batch_size" in text
    assert "ray_tpu_serve_batch_utilization" in text
    # batch wait lands in the access-log stage timings too (the
    # replica's bookkeeping drains asynchronously)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        lines = [l for l in _access_log_lines()
                 if l["deployment"] == "Batcher"]
        if any("batch_wait_ms" in l["timings_ms"] for l in lines):
            break
        time.sleep(0.1)
    assert any("batch_wait_ms" in l["timings_ms"] for l in lines)


def test_latency_dashboard_endpoint(serve_instance):
    from ray_tpu.dashboard import start_dashboard

    @serve.deployment
    def hello(x):
        return "hi"

    serve.run(hello.bind(), route_prefix="/hello")
    dash = start_dashboard(port=0, with_jobs=False)
    try:
        assert _get("/hello")[0] == 200
        base = f"http://127.0.0.1:{dash.address[1]}"
        with urllib.request.urlopen(base + "/api/serve/latency",
                                    timeout=10) as r:
            stats = json.loads(r.read())
        assert "hello" in stats
        assert stats["hello"]["requests"] >= 1
        assert stats["hello"]["latency_ms"]["p50"] is not None
        # the serve access logs are browsable through the per-node
        # dashboard agent log endpoints (one level of subdirs)
        node_hex = ray_tpu.nodes()[0]["NodeID"]
        # the replica's bookkeeping drains asynchronously (~50ms cadence)
        deadline = time.monotonic() + 10
        serve_logs, logs = [], []
        while time.monotonic() < deadline and not serve_logs:
            with urllib.request.urlopen(
                    f"{base}/api/nodes/{node_hex}/logs",
                    timeout=10) as r:
                logs = json.loads(r.read())
            serve_logs = [l["name"] for l in logs
                          if l["name"].startswith("serve/")]
            time.sleep(0.1)
        assert serve_logs, logs
        # the replica's access-log flusher is async (~0.2s cadence)
        deadline = time.monotonic() + 10
        tail = {"text": ""}
        while time.monotonic() < deadline \
                and "request_id" not in tail["text"]:
            with urllib.request.urlopen(
                    f"{base}/api/nodes/{node_hex}/logs/{serve_logs[0]}",
                    timeout=10) as r:
                tail = json.loads(r.read())
            time.sleep(0.1)
        assert "request_id" in tail["text"]
    finally:
        dash.stop()


def test_observability_disabled_is_clean(monkeypatch):
    """With RAY_TPU_SERVE_OBSERVABILITY_ENABLED=0 the request path runs
    uninstrumented: no serve histograms, no access logs (the
    bench_serve.py baseline mode)."""
    from ray_tpu.core.config import global_config

    monkeypatch.setattr(global_config(),
                        "serve_observability_enabled", False)
    ray_tpu.init(num_cpus=2, num_tpus=0)
    try:
        serve.start(serve.HTTPOptions(port=PORT))

        @serve.deployment
        def plain(x):
            return {"v": x}

        handle = serve.run(plain.bind(), route_prefix=None)
        assert handle.remote(3).result() == {"v": 3}
        d = os.path.join(api._get_head().session_dir, "logs", "serve")
        assert not os.path.isdir(d) or not os.listdir(d)
        assert _merged_latency_count("plain") == 0
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
