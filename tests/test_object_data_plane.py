"""Zero-copy object data plane: pooled connections, arena-direct receive,
striped multi-peer pulls with failover.

Exercises ray_tpu/core/object_transfer.py at the store/server level (real
TCP + HMAC, no cluster needed) plus one end-to-end pull through a cluster.
"""

import tempfile
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core import serialization
from ray_tpu.core.config import global_config
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import LocalObjectStore
from ray_tpu.core.object_transfer import (
    ConnectionPool,
    ObjectServer,
    _pool,
    pool_stats,
    pull_object,
    pull_object_striped,
    push_object,
)

KEY = b"data-plane-test!"


@pytest.fixture
def stores():
    """Two server-backed stores + one destination store."""
    made = []

    def make(hexname):
        s = LocalObjectStore(tempfile.mkdtemp(), hexname, capacity=256 << 20)
        made.append(s)
        return s

    s1, s2, dest = make("aa" * 8), make("bb" * 8), make("cc" * 8)
    srv1, srv2 = ObjectServer(s1, KEY), ObjectServer(s2, KEY)
    try:
        yield s1, srv1, s2, srv2, dest
    finally:
        srv1.close()
        srv2.close()
        for s in made:
            s.close()


def _seal(store, value):
    """Serialize ``value`` into ``store`` the way the runtime does."""
    oid = ObjectID.from_random()
    sobj = serialization.serialize(value)
    cfg = global_config()
    if sobj.total_bytes <= cfg.max_direct_call_object_size:
        store.put_inline(oid, sobj.to_bytes(), False)
    else:
        _, view = store.create(oid, sobj.total_bytes)
        sobj.write_into_view(view)
        store.seal(oid, False)
    return oid


def _read_back(store, oid):
    payload, is_err = store.get_payload(oid)
    assert not is_err
    return serialization.deserialize(payload)


class TestArenaDirectReceive:
    """Byte-identical round trips through the arena-direct pull path."""

    def test_inline_value(self, stores):
        s1, srv1, _s2, _srv2, dest = stores
        oid = _seal(s1, {"k": [1, 2, 3], "s": "inline"})
        body, is_err = pull_object(srv1.address, KEY, oid, dest_store=dest)
        assert not is_err and isinstance(body, bytes)
        assert serialization.deserialize(body) == {"k": [1, 2, 3],
                                                   "s": "inline"}

    def test_single_buffer_value(self, stores):
        s1, srv1, _s2, _srv2, dest = stores
        arr = (np.arange(3 << 20, dtype=np.uint8) * 7) % 251
        oid = _seal(s1, arr)
        body, is_err = pull_object(srv1.address, KEY, oid, dest_store=dest)
        assert not is_err and isinstance(body, tuple) and body[0] == "arena"
        out = _read_back(dest, oid)
        assert out.dtype == arr.dtype and np.array_equal(out, arr)

    def test_multi_buffer_value(self, stores):
        """Pickle-5 out-of-band: several buffers in one sealed object."""
        s1, srv1, _s2, _srv2, dest = stores
        value = {
            "a": np.arange(1 << 20, dtype=np.float32),
            "b": np.full(2 << 20, 0x5A, dtype=np.uint8),
            "meta": ("tag", 42),
        }
        oid = _seal(s1, value)
        body, is_err = pull_object(srv1.address, KEY, oid, dest_store=dest)
        assert not is_err and isinstance(body, tuple)
        out = _read_back(dest, oid)
        assert np.array_equal(out["a"], value["a"])
        assert np.array_equal(out["b"], value["b"])
        assert out["meta"] == ("tag", 42)

    def test_pull_without_dest_store(self, stores):
        s1, srv1, _s2, _srv2, _dest = stores
        arr = np.ones(2 << 20, dtype=np.uint8)
        oid = _seal(s1, arr)
        body, is_err = pull_object(srv1.address, KEY, oid, dest_store=None)
        assert not is_err
        assert np.array_equal(serialization.deserialize(body), arr)


class TestConnectionPool:
    def test_sequential_pulls_reuse_the_socket(self, stores):
        """Pooled reuse is observable: the second pull checks out the very
        connection object the first returned, and hit/miss counters move."""
        s1, srv1, _s2, _srv2, dest = stores
        addr = tuple(srv1.address)
        before = pool_stats()
        oid1 = _seal(s1, np.ones(1 << 20, dtype=np.uint8))
        oid2 = _seal(s1, np.zeros(1 << 20, dtype=np.uint8))
        assert pull_object(addr, KEY, oid1, dest_store=dest) is not None
        idle = list(_pool._idle.get(addr, ()))
        assert idle, "connection was not returned to the pool"
        first_conn = idle[-1][0]
        assert pull_object(addr, KEY, oid2, dest_store=dest) is not None
        idle2 = list(_pool._idle.get(addr, ()))
        assert idle2 and idle2[-1][0] is first_conn, \
            "second pull did not reuse the pooled socket"
        after = pool_stats()
        assert after["hits"] >= before["hits"] + 1
        assert after["misses"] >= before["misses"] + 1

    def test_bounded_size_and_health_check(self):
        pool = ConnectionPool()

        class FakeConn:
            closed = False

            def poll(self, _t):
                return False

            def close(self):
                self.closed = True

        cfg = global_config()
        cap = cfg.object_pool_connections_per_peer
        conns = [FakeConn() for _ in range(cap + 2)]
        for c in conns:
            pool.release(("h", 1), c)
        assert pool.stats()["idle"] <= cap
        # dead connection is discarded at checkout, not handed out
        dead = FakeConn()
        dead.closed = True
        pool.release(("h", 2), dead)
        with pytest.raises(Exception):
            # checkout sees the dead conn, drops it, then dials a fresh
            # connection to a port nothing listens on
            pool.acquire(("127.0.0.1", 1), KEY)
        assert dead.closed


class TestStripedPull:
    def test_striped_pull_is_byte_identical(self, stores):
        s1, srv1, s2, srv2, dest = stores
        cfg = global_config()
        old = cfg.object_stripe_threshold
        cfg.object_stripe_threshold = 1 << 20
        try:
            arr = (np.arange(20 << 20, dtype=np.uint8) * 13) % 241
            sobj = serialization.serialize(arr)
            oid = ObjectID.from_random()
            for s in (s1, s2):
                _, view = s.create(oid, sobj.total_bytes)
                sobj.write_into_view(view)
                s.seal(oid, False)
            before = pool_stats()
            res = pull_object_striped([srv1.address, srv2.address], KEY,
                                      oid, dest)
            assert res is not None and isinstance(res[0], tuple)
            assert np.array_equal(_read_back(dest, oid), arr)
        finally:
            cfg.object_stripe_threshold = old

    def test_striped_pull_survives_holder_death_mid_transfer(self, stores):
        """Kill one holder while its stripe streams; the stripe must fail
        over to the surviving holder and the object must still verify."""
        s1, srv1, s2, srv2, dest = stores
        cfg = global_config()
        old_thr, old_chunk = (cfg.object_stripe_threshold,
                              cfg.object_transfer_chunk_size)
        cfg.object_stripe_threshold = 1 << 20
        cfg.object_transfer_chunk_size = 256 << 10  # many frames per stripe
        try:
            arr = (np.arange(32 << 20, dtype=np.uint8) * 31) % 233
            sobj = serialization.serialize(arr)
            oid = ObjectID.from_random()
            for s in (s1, s2):
                _, view = s.create(oid, sobj.total_bytes)
                sobj.write_into_view(view)
                s.seal(oid, False)

            killer = threading.Timer(0.02, srv2.close)
            killer.start()
            try:
                res = pull_object_striped([srv1.address, srv2.address], KEY,
                                          oid, dest)
            finally:
                killer.cancel()
            assert res is not None, "striped pull died with the holder"
            assert np.array_equal(_read_back(dest, oid), arr)
        finally:
            cfg.object_stripe_threshold = old_thr
            cfg.object_transfer_chunk_size = old_chunk

    def test_striped_pull_all_holders_dead_returns_none(self, stores):
        s1, srv1, s2, srv2, dest = stores
        oid = _seal(s1, np.ones(9 << 20, dtype=np.uint8))
        srv1.close()
        srv2.close()
        time.sleep(0.05)
        res = pull_object_striped([srv1.address, srv2.address], KEY, oid,
                                  dest)
        assert res is None
        assert not dest.contains(oid)


class TestPushPath:
    def test_push_arena_direct(self, stores):
        s1, srv1, _s2, _srv2, dest = stores
        srv_dest = ObjectServer(dest, KEY)
        try:
            arr = np.arange(4 << 20, dtype=np.uint8) % 199
            oid = _seal(s1, arr)
            assert push_object(srv_dest.address, KEY, oid, s1)
            assert dest.contains(oid)
            assert np.array_equal(_read_back(dest, oid), arr)
        finally:
            srv_dest.close()

    def test_push_missing_object_returns_false(self, stores):
        s1, srv1, _s2, _srv2, dest = stores
        srv_dest = ObjectServer(dest, KEY)
        try:
            assert not push_object(srv_dest.address, KEY,
                                   ObjectID.from_random(), s1)
        finally:
            srv_dest.close()


def test_open_read_defers_free_during_delete(stores=None):
    """delete() during an open_read send must not free the extent under
    the reader; the free happens at release."""
    store = LocalObjectStore(tempfile.mkdtemp(), "dd" * 8,
                             capacity=64 << 20)
    try:
        oid = ObjectID.from_random()
        payload = b"z" * (2 << 20)
        _, view = store.create(oid, len(payload))
        view[:] = payload
        store.seal(oid, False)
        allocated = store.arena.allocator.bytes_allocated()
        with store.open_read(oid) as handle:
            assert handle is not None
            store.delete(oid)
            # still pinned: bytes must remain readable and allocated
            assert bytes(handle.view[:8]) == b"zzzzzzzz"
            assert store.arena.allocator.bytes_allocated() == allocated
        # released: extent returned to the allocator
        assert store.arena.allocator.bytes_allocated() < allocated
    finally:
        store.close()


@pytest.mark.slow
def test_end_to_end_remote_pull_uses_pool(ray_start_cluster):
    """A real 2-process transfer goes through the pooled data plane."""
    cluster = ray_start_cluster
    cluster.connect()
    cluster.add_node(num_cpus=1, resources={"src": 2},
                     separate_process=True)

    @ray_tpu.remote(resources={"src": 1})
    def produce(n):
        return np.full(n, 7, dtype=np.uint8)

    before = pool_stats()
    a = ray_tpu.get(produce.remote(2 << 20), timeout=120)
    b = ray_tpu.get(produce.remote(3 << 20), timeout=120)
    assert a.nbytes == 2 << 20 and b.nbytes == 3 << 20
    after = pool_stats()
    assert after["misses"] >= before["misses"]
    assert (after["hits"], after["misses"]) != (before["hits"],
                                                before["misses"])
