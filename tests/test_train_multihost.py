"""Multi-host Train bootstrap: real node IPs in coordinator payloads.

Round-2 weak item #2: TrainWorker.get_metadata hardcoded 127.0.0.1, so
JaxBackend built a coordinator address that only worked single-machine
(reference: train/torch/xla/config.py:41-67 builds the rendezvous from real
worker IPs). Daemons now advertise a routable node_ip that flows through
worker init -> runtime_context -> Train metadata.
"""

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train.backend_executor import JaxBackend, TrainWorker
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


@pytest.fixture
def two_ip_cluster():
    c = Cluster(head_node_args={"num_cpus": 1})
    n1 = c.add_node(num_cpus=1, separate_process=True, node_ip="127.0.0.2")
    n2 = c.add_node(num_cpus=1, separate_process=True, node_ip="127.0.0.3")
    yield c, n1, n2
    c.shutdown()


def test_distinct_node_ips_in_coordinator_payloads(two_ip_cluster):
    c, n1, n2 = two_ip_cluster
    WorkerActor = ray_tpu.remote(TrainWorker)
    actors = []
    for rank, node in enumerate((n1, n2)):
        strat = NodeAffinitySchedulingStrategy(node_id=node.hex, soft=False)
        actors.append(WorkerActor.options(
            num_cpus=1, scheduling_strategy=strat).remote(
            2, rank, 0, rank, "exp", "/tmp/trial"))
    metadata = ray_tpu.get([a.get_metadata.remote() for a in actors],
                           timeout=120)
    ips = [m["ip"] for m in metadata]
    assert ips == ["127.0.0.2", "127.0.0.3"], ips

    payloads = JaxBackend(coordinator_port=9123).on_start(metadata)
    # worker 0 hosts the coordinator: every worker must be handed ITS
    # address, not loopback
    for i, p in enumerate(payloads):
        jd = p["jax_distributed"]
        assert jd["coordinator_address"] == "127.0.0.2:9123"
        assert jd["num_processes"] == 2 and jd["process_id"] == i
        assert p["env"]["JAX_COORDINATOR_ADDRESS"] == "127.0.0.2:9123"


def test_runtime_context_node_ip_defaults_loopback():
    ray_tpu.init(num_cpus=1)
    try:
        @ray_tpu.remote
        def ip():
            return ray_tpu.get_runtime_context().get_node_ip()

        assert ray_tpu.get(ip.remote()) == "127.0.0.1"
    finally:
        ray_tpu.shutdown()
