"""Core runtime microbenchmarks.

Port of the reference's microbenchmark op set
(/root/reference/python/ray/_private/ray_perf.py:120-315): put/get rates,
task submit/round-trip rates, actor call rates, wait. Run:

    python bench_core.py [--ops op1,op2] [--json]

Prints one line per op; with --json, a JSON object of all results. These
are the regression gates for the control/object planes (the tensor plane is
bench.py's job).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time


def timeit(name, fn, multiplier=1, warmup=1, min_time=1.0):
    for _ in range(warmup):
        fn()
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < min_time:
        fn()
        count += 1
    dt = time.perf_counter() - start
    rate = count * multiplier / dt
    print(f"{name:<42s} {rate:>12.1f} /s")
    return rate


# --------------------------------------------------------------------------- #
# Head-free actor plane bench (BENCH_ACTOR.json)
#
# Proves the actor/stream data plane does not ride through the head: the
# same workload runs with the head's control loop artificially slowed
# (RAY_TPU_TEST_HEAD_DELAY_MS, injected into every head-served RPC) and
# direct actor-call p50 / cross-process stream items/s must not move,
# while ray_tpu_head_rpcs_total stays flat during the steady state.
# Methodology per ADVICE.md: one subprocess per (delay, rep), reps
# interleaved across modes, min-of-rounds aggregation.
# --------------------------------------------------------------------------- #

ACTOR_CALLS = 200
STREAM_ITEMS = 400


def _actor_bench_child() -> dict:
    """One measured cluster run; RAY_TPU_TEST_HEAD_DELAY_MS set by the
    parent. Prints one JSON line."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.metrics import registry

    def head_rpcs() -> float:
        m = registry().snapshot().get("ray_tpu_head_rpcs_total")
        if not m:
            return 0.0
        return sum(m["values"].values())

    cluster = Cluster(head_node_args={"num_cpus": 2})
    cluster.add_node(num_cpus=2, resources={"far": 2},
                     separate_process=True)

    @ray_tpu.remote(resources={"far": 1})
    class A:
        def m(self, x):
            return x

        def stream(self, n):
            for i in range(n):
                yield i

    @ray_tpu.remote(resources={"far": 1})
    def consume(g):
        t0 = time.perf_counter()
        n = sum(1 for _ in g)
        return n, time.perf_counter() - t0

    @ray_tpu.remote
    def gen(n):
        for i in range(n):
            yield i

    a = A.remote()
    ray_tpu.get(a.m.remote(0))  # creation + route resolution (head ops OK)
    # Warm every path the steady state exercises: peer channels, stream
    # subscription both directions, worker function caches. Cold-start
    # head ops (get_function, actor_location) are one-time costs and are
    # excluded from the steady-state flatness measurement.
    g = a.stream.options(num_returns="streaming").remote(5)
    assert ray_tpu.get(consume.remote(g))[0] == 5
    assert ray_tpu.get(consume.remote(
        gen.options(num_returns="streaming").remote(5)))[0] == 5
    assert sum(1 for _ in a.stream.options(
        num_returns="streaming").remote(5)) == 5

    out = {"head_delay_ms": int(os.environ.get(
        "RAY_TPU_TEST_HEAD_DELAY_MS", "0"))}

    # --- steady-state direct actor calls (sequential round trips);
    # the head-RPC counter must not move across this loop ---
    rpcs0 = head_rpcs()
    lat = []
    for i in range(ACTOR_CALLS):
        t0 = time.perf_counter()
        ray_tpu.get(a.m.remote(i))
        lat.append(time.perf_counter() - t0)
    delta = head_rpcs() - rpcs0
    out["actor_call_p50_ms"] = round(
        statistics.median(lat) * 1e3, 4)

    # --- cross-process stream: the consumer task (daemon worker)
    # subscribes to the DRIVER-owned generator task's stream. The
    # harness task itself (consume, head-path custom-resource spec) may
    # cold-start a worker (get_function) — the stream-plane measurement
    # is the in-consumer items/s, so the rpc-flatness window covers the
    # driver-side stream consumption below instead. ---
    items, dt = ray_tpu.get(consume.remote(
        gen.options(num_returns="streaming").remote(STREAM_ITEMS)))
    assert items == STREAM_ITEMS
    out["stream_items_per_s"] = round(items / dt, 1)
    # reverse direction: daemon-actor stream consumed by the driver —
    # pure stream plane, inside the flatness window
    rpcs1 = head_rpcs()
    t0 = time.perf_counter()
    n = sum(1 for _ in a.stream.options(
        num_returns="streaming").remote(STREAM_ITEMS))
    assert n == STREAM_ITEMS
    delta += head_rpcs() - rpcs1
    out["actor_stream_items_per_s"] = round(
        STREAM_ITEMS / (time.perf_counter() - t0), 1)

    out["head_rpcs_steady_delta"] = delta
    cluster.shutdown()
    print(json.dumps(out))
    return out


def _actor_bench(reps: int, check: bool) -> int:
    delays = [0, 50]
    runs = {d: [] for d in delays}
    for rep in range(reps):
        order = delays if rep % 2 == 0 else delays[::-1]  # interleaved
        for d in order:
            env = dict(os.environ)
            env["RAY_TPU_TEST_HEAD_DELAY_MS"] = str(d)
            env["JAX_PLATFORMS"] = "cpu"
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--actor-bench-child"],
                env=env, capture_output=True, text=True, timeout=600,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            line = [ln for ln in p.stdout.splitlines()
                    if ln.startswith("{")]
            if p.returncode != 0 or not line:
                print(p.stdout[-2000:], file=sys.stderr)
                print(p.stderr[-2000:], file=sys.stderr)
                raise RuntimeError(f"actor-bench child failed (delay={d})")
            rec = json.loads(line[-1])
            runs[d].append(rec)
            print(f"# rep={rep} delay={d}ms "
                  f"p50={rec['actor_call_p50_ms']}ms "
                  f"stream={rec['stream_items_per_s']}/s "
                  f"actor_stream={rec['actor_stream_items_per_s']}/s "
                  f"head_rpcs_delta={rec['head_rpcs_steady_delta']}",
                  file=sys.stderr)

    def best(d, key, lo_is_good):
        vals = [r[key] for r in runs[d]]
        return min(vals) if lo_is_good else max(vals)

    result = {
        "method": f"{reps} interleaved subprocess reps per delay, "
                  "min-of-rounds (ADVICE.md)",
        "calls": ACTOR_CALLS, "stream_items": STREAM_ITEMS,
        "actor_call_p50_ms": {
            str(d): best(d, "actor_call_p50_ms", True) for d in delays},
        "stream_items_per_s": {
            str(d): best(d, "stream_items_per_s", False) for d in delays},
        "actor_stream_items_per_s": {
            str(d): best(d, "actor_stream_items_per_s", False)
            for d in delays},
        "head_rpcs_steady_delta_max": max(
            r["head_rpcs_steady_delta"] for d in delays for r in runs[d]),
    }
    p50_ratio = (result["actor_call_p50_ms"]["50"]
                 / max(result["actor_call_p50_ms"]["0"], 1e-9))
    stream_ratio = (result["stream_items_per_s"]["50"]
                    / max(result["stream_items_per_s"]["0"], 1e-9))
    astream_ratio = (result["actor_stream_items_per_s"]["50"]
                     / max(result["actor_stream_items_per_s"]["0"], 1e-9))
    result["p50_slowdown_with_head_delay"] = round(p50_ratio, 4)
    result["stream_speed_ratio_with_head_delay"] = round(stream_ratio, 4)
    result["actor_stream_speed_ratio_with_head_delay"] = round(
        astream_ratio, 4)
    gates = {
        "p50_within_10pct": p50_ratio <= 1.10,
        "stream_within_10pct": stream_ratio >= 0.90,
        "actor_stream_within_10pct": astream_ratio >= 0.90,
        "head_rpcs_flat": result["head_rpcs_steady_delta_max"] == 0,
    }
    result["check"] = gates
    result["check_passed"] = all(gates.values())
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_ACTOR.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    if check and not result["check_passed"]:
        print("ACTOR BENCH CHECK FAILED", file=sys.stderr)
        return 1
    return 0


# --------------------------------------------------------------------------- #
# Compiled-graph data-plane bench (BENCH_DAG.json)
#
# Five measurements per child run (ROADMAP: microsecond dispatch + MPMD +
# cross-host rings):
#  1. per-hop dispatch: compiled 1-stage execute+get round trip vs
#     ray_tpu.get(actor.m.remote()) — the >=10x gate.
#  2. pipelining: 4-stage chain throughput with max_inflight=8 vs
#     max_inflight=1 (lockstep) on sleep-bound stages — sleeps overlap
#     regardless of host core count, so the ratio isolates the ring
#     channels' overlap from CPU contention. The >=2x gate.
#  3. cross-daemon hop: the SAME 1-stage compiled round trip against an
#     actor on a separate-process daemon — every edge a NetRing over
#     authenticated TCP instead of /dev/shm. Gate: within 10x of the
#     shm hop measured in the same child.
#  4. MPMD pipeline trainer at K=4 stages, M=16 microbatches: measured
#     bubble fraction for the 1F1B schedule (gate < 0.25) vs gpipe
#     (reported), fresh actors per schedule, order alternated across
#     reps; distributed losses must match the in-process reference.
#  5. tensor-path proof: stage serialized-bytes stay 0 across both.
# Methodology per ADVICE.md: subprocess per rep, modes interleaved inside
# each child, min-of-rounds (best round per mode) aggregation.
# --------------------------------------------------------------------------- #

DAG_DISPATCH_CALLS = 150
DAG_NET_CALLS = 60
DAG_PIPE_EXECS = 40
DAG_STAGE_SLEEP_S = 0.002
# MB-scale activation throughput: a 4 MB float32 "activation" (the
# microbatch-activation size class pipeline stages actually ship)
# echoed through a 1-stage compiled graph — shm rings and net rings
# measured with the SAME payload so the MB/s are directly comparable.
DAG_ACT_BYTES = 4 << 20
DAG_ACT_CALLS = 24
DAG_ACT_NET_CALLS = 10
MPMD_STAGES = 4
MPMD_MICROBATCHES = 16
MPMD_VIRTUAL = 2  # interleaved 1F1B: 2 chunks per stage actor
MPMD_STEPS = 2
# 8 hidden d x d layers + a d x d head: 9 params over 8 chunks puts one
# REAL layer on every chunk (incl. the loss chunk), so per-actor work is
# balanced and the measured bubble reflects the schedule, not a lopsided
# model split. ~34 MFLOP per chunk call at microbatch 16 rows.
MPMD_DIM = 2048
MPMD_LAYERS = [64] + [MPMD_DIM] * 8 + [MPMD_DIM]
MPMD_BATCH = 256


def _dag_bench_child() -> dict:
    import ray_tpu
    from ray_tpu.dag import InputNode

    ray_tpu.init(num_cpus=4, num_tpus=0)

    @ray_tpu.remote
    class Echo:
        def m(self, x):
            return x

        def s(self, x):
            time.sleep(DAG_STAGE_SLEEP_S)
            return x

    out = {}
    payload = b"x" * 64

    # --- 1. per-hop dispatch: compiled vs remote(), interleaved rounds ---
    a = Echo.remote()
    ray_tpu.get(a.m.remote(payload))
    with InputNode() as inp:
        node = a.m.bind(inp)
    compiled = node.experimental_compile()
    try:
        compiled.execute(payload).get()  # warm the resident loop

        def remote_round():
            t0 = time.perf_counter()
            for _ in range(DAG_DISPATCH_CALLS):
                ray_tpu.get(a.m.remote(payload))
            return (time.perf_counter() - t0) / DAG_DISPATCH_CALLS

        def compiled_round():
            t0 = time.perf_counter()
            for _ in range(DAG_DISPATCH_CALLS):
                compiled.execute(payload).get()
            return (time.perf_counter() - t0) / DAG_DISPATCH_CALLS

        remote_s, compiled_s = [], []
        for r in range(3):
            if r % 2 == 0:
                remote_s.append(remote_round())
                compiled_s.append(compiled_round())
            else:
                compiled_s.append(compiled_round())
                remote_s.append(remote_round())
        out["remote_per_call_us"] = round(min(remote_s) * 1e6, 2)
        out["compiled_per_hop_us"] = round(min(compiled_s) * 1e6, 2)
        out["dispatch_speedup"] = round(min(remote_s) / min(compiled_s), 2)
    finally:
        compiled.teardown()

    # --- 1b. MB-scale activation throughput over the shm ring ---
    # Same 1-stage echo shape as measurement 1, but the payload is a
    # 4 MB float32 array riding the tensor path and the ring slots are
    # sized to hold it. Each execute+get moves the buffer through both
    # compiled edges; MB/s below counts one-way payload per round trip,
    # so the raw ring byte rate is ~2x the reported number.
    import numpy as np

    act = np.zeros(DAG_ACT_BYTES // 4, dtype=np.float32)

    def act_round(dag, calls):
        t0 = time.perf_counter()
        for _ in range(calls):
            dag.execute(act).get()
        return calls * (DAG_ACT_BYTES / 1e6) / (time.perf_counter() - t0)

    with InputNode() as inp:
        node = a.m.bind(inp)
    act_dag = node.experimental_compile(
        buffer_size_bytes=DAG_ACT_BYTES + (1 << 20))
    try:
        act_dag.execute(act).get()  # warm
        shm_tp = [act_round(act_dag, DAG_ACT_CALLS) for _ in range(3)]
        out["shm_activation_mb_s"] = round(max(shm_tp), 1)
    finally:
        act_dag.teardown()
    out["activation_payload_mb"] = round(DAG_ACT_BYTES / 1e6, 2)

    # --- 2. pipelined vs lockstep on a 4-stage sleep-bound chain ---
    stages = [Echo.remote() for _ in range(4)]
    ray_tpu.get([s.m.remote(0) for s in stages])

    def chain_throughput(max_inflight: int) -> float:
        with InputNode() as inp:
            node = inp
            for s in stages:
                node = s.s.bind(node)
        dag = node.experimental_compile(max_inflight=max_inflight)
        try:
            dag.execute(payload).get()  # warm
            # sliding window of max_inflight outstanding: lockstep (1)
            # degenerates to submit-get-submit; pipelined keeps the
            # rings full without outrunning the output ring
            import collections as _c

            pending = _c.deque()
            t0 = time.perf_counter()
            for _ in range(DAG_PIPE_EXECS):
                if len(pending) >= max_inflight:
                    pending.popleft().get(timeout=120)
                pending.append(dag.execute(payload))
            while pending:
                pending.popleft().get(timeout=120)
            return DAG_PIPE_EXECS / (time.perf_counter() - t0)
        finally:
            dag.teardown()

    lockstep, pipelined = [], []
    for r in range(2):
        if r % 2 == 0:
            lockstep.append(chain_throughput(1))
            pipelined.append(chain_throughput(8))
        else:
            pipelined.append(chain_throughput(8))
            lockstep.append(chain_throughput(1))
    out["lockstep_execs_per_s"] = round(max(lockstep), 2)
    out["pipelined_execs_per_s"] = round(max(pipelined), 2)
    out["pipeline_speedup"] = round(max(pipelined) / max(lockstep), 2)

    # --- 3. cross-daemon hop: the same 1-stage round trip over NetRings ---
    # A separate-process daemon joins over TCP; the actor is pinned
    # there, so both compiled edges (driver->stage, stage->driver) are
    # net rings. Same call shape as measurement 1 => directly
    # comparable per-hop numbers.
    from ray_tpu.cluster_utils import Cluster as _Cluster
    from ray_tpu.core import api as _api

    cluster = _Cluster(initialize_head=False)  # ride the running head
    cluster.head = _api._head
    cluster.add_node(num_cpus=2, resources={"net": 4},
                     separate_process=True)

    far = Echo.options(resources={"net": 1}).remote()
    ray_tpu.get(far.m.remote(payload))
    with InputNode() as inp:
        node = far.m.bind(inp)
    net_dag = node.experimental_compile()
    try:
        from ray_tpu.core.net_ring import NetRingWriter

        assert isinstance(net_dag._input_chans[0], NetRingWriter), \
            "cross-daemon edge did not resolve to a net ring"
        net_dag.execute(payload).get()  # warm the loop + session

        def net_round():
            t0 = time.perf_counter()
            for _ in range(DAG_NET_CALLS):
                net_dag.execute(payload).get()
            return (time.perf_counter() - t0) / DAG_NET_CALLS

        net_s = [net_round() for _ in range(3)]
        out["net_per_hop_us"] = round(min(net_s) * 1e6, 2)
        out["net_vs_shm_hop_ratio"] = round(
            out["net_per_hop_us"] / out["compiled_per_hop_us"], 2)
    finally:
        net_dag.teardown()

    # --- 3b. the same 4 MB activation over the net ring ---
    with InputNode() as inp:
        node = far.m.bind(inp)
    net_act = node.experimental_compile(
        buffer_size_bytes=DAG_ACT_BYTES + (1 << 20))
    try:
        net_act.execute(act).get()  # warm
        net_tp = [act_round(net_act, DAG_ACT_NET_CALLS) for _ in range(3)]
        out["net_activation_mb_s"] = round(max(net_tp), 1)
    finally:
        net_act.teardown()

    # --- 4. MPMD trainer bubble at K=4, M=16: 1f1b vs gpipe ---
    from ray_tpu.train import MPMDPipelineTrainer
    from ray_tpu.train.pipeline import reference_train_losses

    rng = np.random.RandomState(0)
    x = rng.randn(MPMD_BATCH, MPMD_LAYERS[0]).astype(np.float32)
    y = rng.randn(MPMD_BATCH, MPMD_LAYERS[-1]).astype(np.float32)

    def mpmd_run(schedule: str):
        # 1F1B runs INTERLEAVED (v chunks per actor, Megatron-style);
        # gpipe is the plain PR-8 sliding-window order for comparison
        v = MPMD_VIRTUAL if schedule == "1f1b" else 1
        trainer = MPMDPipelineTrainer(MPMD_LAYERS, num_stages=MPMD_STAGES,
                                      lr=0.05, schedule=schedule,
                                      virtual_stages=v)
        try:
            losses = trainer.fit(x, y, steps=MPMD_STEPS,
                                 num_microbatches=MPMD_MICROBATCHES)
            st = trainer.pipeline_stats()
            ser = sum(cs["serialized_bytes"]
                      for cs in trainer.channel_stats())
            return losses, st, ser
        finally:
            trainer.shutdown()

    # alternate schedule order across reps (rep index via env)
    order = ("1f1b", "gpipe") if int(os.environ.get(
        "DAG_BENCH_REP", "0")) % 2 == 0 else ("gpipe", "1f1b")
    results = {}
    for schedule in order:
        results[schedule] = mpmd_run(schedule)
    # one in-process replay (the chunk split only regroups the chain
    # rule — losses are split-invariant to fp noise, so one reference
    # covers both schedules)
    ref = reference_train_losses(
        MPMD_LAYERS, 0, x, y, steps=MPMD_STEPS,
        num_microbatches=MPMD_MICROBATCHES,
        num_stages=MPMD_STAGES * MPMD_VIRTUAL, lr=0.05)
    for schedule, (losses, st, ser) in results.items():
        key = schedule
        out[f"mpmd_bubble_{key}"] = st["bubble_fraction"]
        out[f"mpmd_efficiency_{key}"] = st["pipeline_efficiency"]
        out[f"mpmd_loss_match_{key}"] = bool(
            np.allclose(losses, ref, rtol=1e-3, atol=1e-5))
        out.setdefault("mpmd_serialized_bytes", 0)
        out["mpmd_serialized_bytes"] += ser
    out["mpmd_stash_max_1f1b"] = results["1f1b"][1]["stash_max"]
    out["mpmd_window_1f1b"] = results["1f1b"][1]["window"]

    for p in cluster._procs:  # reap the bench daemon before exiting
        try:
            p.terminate()
            p.wait(timeout=5)
        except Exception:
            pass
    ray_tpu.shutdown()
    print(json.dumps(out))
    return out


def _dag_bench(reps: int, check: bool) -> int:
    runs = []
    for rep in range(reps):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["DAG_BENCH_REP"] = str(rep)  # alternates mpmd schedule order
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--dag-bench-child"],
            env=env, capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        line = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
        if p.returncode != 0 or not line:
            print(p.stdout[-2000:], file=sys.stderr)
            print(p.stderr[-2000:], file=sys.stderr)
            raise RuntimeError("dag-bench child failed")
        rec = json.loads(line[-1])
        runs.append(rec)
        print(f"# rep={rep} dispatch={rec['dispatch_speedup']}x "
              f"(remote {rec['remote_per_call_us']}us vs compiled "
              f"{rec['compiled_per_hop_us']}us, net "
              f"{rec['net_per_hop_us']}us) "
              f"pipeline={rec['pipeline_speedup']}x "
              f"act shm={rec['shm_activation_mb_s']}MB/s "
              f"net={rec['net_activation_mb_s']}MB/s "
              f"bubble 1f1b={rec['mpmd_bubble_1f1b']} "
              f"gpipe={rec['mpmd_bubble_gpipe']}", file=sys.stderr)

    def best(key, lo_is_good):
        vals = [r[key] for r in runs]
        return min(vals) if lo_is_good else max(vals)

    result = {
        "method": f"{reps} subprocess reps, modes interleaved inside each "
                  "child, min-of-rounds (ADVICE.md)",
        "dispatch_calls": DAG_DISPATCH_CALLS,
        "pipeline_execs": DAG_PIPE_EXECS,
        "stage_sleep_s": DAG_STAGE_SLEEP_S,
        "remote_per_call_us": best("remote_per_call_us", True),
        "compiled_per_hop_us": best("compiled_per_hop_us", True),
        "dispatch_speedup": best("dispatch_speedup", False),
        "net_per_hop_us": best("net_per_hop_us", True),
        "activation_payload_mb": runs[0]["activation_payload_mb"],
        "shm_activation_mb_s": best("shm_activation_mb_s", False),
        "net_activation_mb_s": best("net_activation_mb_s", False),
        "lockstep_execs_per_s": best("lockstep_execs_per_s", False),
        "pipelined_execs_per_s": best("pipelined_execs_per_s", False),
        "pipeline_speedup": best("pipeline_speedup", False),
        "mpmd_stages": MPMD_STAGES,
        "mpmd_virtual_stages": MPMD_VIRTUAL,
        "mpmd_microbatches": MPMD_MICROBATCHES,
        "mpmd_bubble_1f1b": best("mpmd_bubble_1f1b", True),
        "mpmd_bubble_gpipe": best("mpmd_bubble_gpipe", True),
        "mpmd_stash_max_1f1b": max(
            r["mpmd_stash_max_1f1b"] for r in runs),
        "mpmd_window_1f1b": runs[0]["mpmd_window_1f1b"],
        "mpmd_loss_match": all(
            r["mpmd_loss_match_1f1b"] and r["mpmd_loss_match_gpipe"]
            for r in runs),
        "mpmd_serialized_bytes_max": max(
            r["mpmd_serialized_bytes"] for r in runs),
    }
    # the cross-host gate compares within-run pairs (same box state),
    # then takes the best ratio across reps
    result["net_vs_shm_hop_ratio"] = best("net_vs_shm_hop_ratio", True)
    # the dispatch ratio and the 1F1B bubble need real parallelism to
    # mean anything: on a 1-cpu host the compiled plane's hybrid spin
    # and the eager pool's workers all fight for the same core (the
    # ratio measures scheduler contention, not dispatch — channel.py
    # documents the 1-core regime), and the MPMD stages' matmuls
    # cannot physically overlap (the measured bubble is core
    # starvation, not the schedule). Same honesty rule as the spmd
    # weak-scaling gate; measured values still recorded for trend.
    multicore = (os.cpu_count() or 1) >= 2
    result["contended_gate_mode"] = "ratio" if multicore else \
        f"trend-only ({os.cpu_count() or 1} cpu: dispatch ratio and " \
        "1F1B bubble measure core oversubscription on this host)"
    gates = {
        "dispatch_10x": (result["dispatch_speedup"] >= 10.0
                         or not multicore),
        "pipelined_2x_lockstep": result["pipeline_speedup"] >= 2.0,
        "net_hop_within_10x_shm": result["net_vs_shm_hop_ratio"] <= 10.0,
        # MB-scale activations must move at memory-ish speed in shm and
        # at least saturate a 10GbE-class link over the net ring —
        # conservative floors so box noise can't flake the gate
        "shm_activation_ge_200_mb_s":
            result["shm_activation_mb_s"] >= 200.0,
        "net_activation_ge_50_mb_s":
            result["net_activation_mb_s"] >= 50.0,
        "bubble_1f1b_lt_0.25": (result["mpmd_bubble_1f1b"] < 0.25
                                or not multicore),
        # the 1F1B memory claim: in-flight (= every chunk's stash)
        # bounded by the schedule window, driver-enforced
        "mpmd_1f1b_stash_bounded":
            result["mpmd_stash_max_1f1b"] <= result["mpmd_window_1f1b"],
        "mpmd_losses_match_reference": result["mpmd_loss_match"],
        "mpmd_tensor_path_only": result["mpmd_serialized_bytes_max"] == 0,
    }
    result["check"] = gates
    result["check_passed"] = all(gates.values())
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_DAG.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    if check and not result["check_passed"]:
        print("DAG BENCH CHECK FAILED", file=sys.stderr)
        return 1
    return 0


# --------------------------------------------------------------------------- #
# Flight-recorder overhead bench (BENCH_TRACE.json)
#
# The always-on claim: tracing every dispatch, ring wait and executor
# span must cost <= 3% on the compiled-graph data plane. The recorder
# gate is toggled IN-PROCESS on both ends between rounds (driver via
# configure(), the worker via a plain actor method) so on/off rounds
# run back-to-back against identical box state — a child per mode
# can't resolve a 3% delta under cross-process scheduling noise, and
# neither can min-per-mode aggregation under slow drift. Estimator:
# p50 per round, per-PAIR delta (each on-round against its adjacent
# off-round), median pair per child, median child across reps.
#
# Two workloads, two gates:
#  - activation path (the dag-bench 4 MB payload, ms-scale per call):
#    relative overhead <= 3% — the tentpole acceptance gate, measured
#    where step time actually goes.
#  - dispatch path (64 B echo, ~tens of us per call): ABSOLUTE delta
#    <= 5 us. 3% of a 45 us round trip is below the paired estimator's
#    noise floor on a shared box, but the recorder's cost there is a
#    fixed clock-read budget (sub-floor spans never reach the ring),
#    so an absolute bound is both measurable and the right invariant
#    (the pre-floor recorder cost 6-17 us and would trip it).
# The child also proves the recorder actually records (span events > 0
# from the above-floor activation hops), so gates can't pass vacuously.
# --------------------------------------------------------------------------- #

TRACE_CALLS = 150      # dispatch-path calls per round
TRACE_ACT_CALLS = 24   # activation-path calls per round
TRACE_ROUNDS = 8       # back-to-back (off, on) round pairs per child


def _trace_bench_child() -> dict:
    import numpy as np

    import ray_tpu
    from ray_tpu.dag import InputNode
    from ray_tpu.util import flight_recorder

    ray_tpu.init(num_cpus=2, num_tpus=0)

    @ray_tpu.remote
    class Echo:
        def m(self, x):
            return x

        def rec(self, on):
            # worker-side recorder toggle for the A/B rounds: spans gate
            # on _on[0] at emit time, so this flips the executor/ring
            # instrumentation without restarting the resident loop
            from ray_tpu.util import flight_recorder as fr

            fr.configure(enabled=bool(on))
            return on

    payload = b"x" * 64
    act = np.zeros(DAG_ACT_BYTES // 4, dtype=np.float32)
    a = Echo.remote()
    b = Echo.remote()
    ray_tpu.get([a.m.remote(payload), b.m.remote(0)])
    with InputNode() as inp:
        node = a.m.bind(inp)
    dag = node.experimental_compile()
    with InputNode() as inp:
        node2 = b.m.bind(inp)
    act_dag = node2.experimental_compile(
        buffer_size_bytes=DAG_ACT_BYTES + (1 << 20))
    out = {}
    try:
        dag.execute(payload).get()  # warm the resident loops
        act_dag.execute(act).get()

        def set_recorder(on):
            flight_recorder.configure(enabled=on)
            ray_tpu.get([a.rec.remote(on), b.rec.remote(on)])

        def round_p50(dag_, calls, payload_):
            durs = []
            for _ in range(calls):
                t0 = time.perf_counter()
                dag_.execute(payload_).get()
                durs.append(time.perf_counter() - t0)
            durs.sort()
            return durs[len(durs) // 2]

        def meas():
            return (round_p50(dag, TRACE_CALLS, payload),
                    round_p50(act_dag, TRACE_ACT_CALLS, act))

        # back-to-back (off, on) pairs, order alternated: the per-pair
        # delta cancels the box's slow drift (which is several times
        # the effect under test); the median pair is the drift-immune
        # overhead estimate
        d_disp, d_act, off_disp, off_act = [], [], [], []
        for r in range(TRACE_ROUNDS):
            if r % 2 == 0:
                set_recorder(False)
                off = meas()
                set_recorder(True)
                on = meas()
            else:
                set_recorder(True)
                on = meas()
                set_recorder(False)
                off = meas()
            d_disp.append(on[0] - off[0])
            d_act.append(on[1] - off[1])
            off_disp.append(off[0])
            off_act.append(off[1])

        def med(vals):
            vals = sorted(vals)
            return vals[len(vals) // 2]

        out["dispatch_p50_off_us"] = round(min(off_disp) * 1e6, 2)
        out["dispatch_delta_us"] = round(med(d_disp) * 1e6, 2)
        out["act_p50_off_us"] = round(min(off_act) * 1e6, 2)
        out["act_delta_us"] = round(med(d_act) * 1e6, 2)
        out["act_overhead_frac"] = round(
            max(0.0, med(d_act)) / min(off_act), 4)
        # proof the on-rounds recorded: the ms-scale activation hops sit
        # above flight_recorder_min_span_us, so their dag.exec /
        # ring-wait spans must be in the driver ring
        snap = flight_recorder.snapshot_payload()
        out["driver_span_events"] = len(snap["events"])
    finally:
        dag.teardown()
        act_dag.teardown()
    ray_tpu.shutdown()
    print(json.dumps(out))
    return out


def _trace_bench(reps: int, check: bool) -> int:
    runs = []
    for rep in range(reps):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--trace-bench-child"],
            env=env, capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        line = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
        if p.returncode != 0 or not line:
            print(p.stdout[-2000:], file=sys.stderr)
            print(p.stderr[-2000:], file=sys.stderr)
            raise RuntimeError("trace-bench child failed")
        rec = json.loads(line[-1])
        runs.append(rec)
        print(f"# rep={rep} disp off={rec['dispatch_p50_off_us']}us "
              f"delta={rec['dispatch_delta_us']}us | act "
              f"off={rec['act_p50_off_us']}us "
              f"delta={rec['act_delta_us']}us "
              f"overhead={rec['act_overhead_frac']} "
              f"(driver events {rec['driver_span_events']})",
              file=sys.stderr)

    def med(key):
        vals = sorted(r[key] for r in runs)
        return vals[len(vals) // 2]

    result = {
        "method": f"{reps} subprocess reps; inside each child the "
                  "recorder is toggled on BOTH ends between back-to-back "
                  "round pairs, median pair delta (drift-immune), then "
                  "median across reps (ADVICE.md)",
        "dispatch_calls_per_round": TRACE_CALLS,
        "act_calls_per_round": TRACE_ACT_CALLS,
        "round_pairs_per_child": TRACE_ROUNDS,
        "act_payload_mb": round(DAG_ACT_BYTES / 1e6, 2),
        "dispatch_p50_off_us": min(
            r["dispatch_p50_off_us"] for r in runs),
        "dispatch_delta_us": med("dispatch_delta_us"),
        "act_p50_off_us": min(r["act_p50_off_us"] for r in runs),
        "act_delta_us": med("act_delta_us"),
        "act_overhead_frac": med("act_overhead_frac"),
        "driver_span_events_min": min(
            r["driver_span_events"] for r in runs),
    }
    gates = {
        # the tentpole acceptance gate: always-on tracing <= 3% on the
        # data plane p50 (ms-scale activation hops)
        "recorder_overhead_le_3pct":
            result["act_overhead_frac"] <= 0.03,
        # the dispatch path pays a fixed clock-read budget per call
        # (sub-floor spans never reach the ring): bound it absolutely
        "dispatch_delta_le_5us": result["dispatch_delta_us"] <= 5.0,
        "recorder_actually_recorded":
            result["driver_span_events_min"] > 0,
    }
    result["check"] = gates
    result["check_passed"] = all(gates.values())
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_TRACE.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    if check and not result["check_passed"]:
        print("TRACE BENCH CHECK FAILED", file=sys.stderr)
        return 1
    return 0


# --------------------------------------------------------------------------- #
# Goodput-observatory overhead bench (BENCH_GOODPUT.json)
#
# The observability claim: the health monitor (badput ledger fold +
# straggler/regression/TTRT detectors, Head._health_monitor_loop) must
# cost <= 1% on an SPMD step loop it is watching. Same estimator as the
# trace bench: the monitor thread is toggled IN-PROCESS between
# back-to-back (off, on) round pairs with alternating order, per-pair
# delta, median pair per child, median child across subprocess reps.
# The bench ticks the monitor every 100 ms — 50x the default 5 s
# cadence — so the gate holds with a wide margin at the real cadence.
# The child also proves the watch is live (ticks > 0, a non-vacuous
# ledger with steps and a goodput fraction) so the gate can't pass
# with the monitor accidentally off.
# --------------------------------------------------------------------------- #

GOODPUT_STEPS = 300       # spmd steps per measured round
GOODPUT_ROUNDS = 8        # back-to-back (off, on) round pairs per child
GOODPUT_TICK_S = 0.1      # monitor cadence under test (default is 5 s)


def _goodput_bench_child() -> dict:
    import threading

    import jax
    import jax.numpy as jnp

    import ray_tpu
    from ray_tpu.core.config import global_config
    from ray_tpu.core.runtime import get_current_runtime
    from ray_tpu.train.health import HealthMonitor
    from ray_tpu.train.spmd import _sp_compute
    from ray_tpu.util import flight_recorder
    from ray_tpu.util.goodput import goodput_report

    # the bench owns the tick cadence: park the head's own monitor
    global_config().health_monitor_enabled = False
    ray_tpu.init(num_cpus=2, num_tpus=0)
    head = get_current_runtime().head
    flight_recorder.configure(enabled=True)

    k = jax.jit(lambda m: m @ m)
    x = jnp.zeros((512, 512), jnp.float32)
    k(x).block_until_ready()               # compile outside the timing

    def step():
        t0 = flight_recorder.now()
        k(x).block_until_ready()
        _sp_compute.end(t0)

    def round_step_s():
        t0 = time.perf_counter()
        for _ in range(GOODPUT_STEPS):
            step()
        return (time.perf_counter() - t0) / GOODPUT_STEPS

    monitor = HealthMonitor(head)
    ticks = [0]

    def meas(on: bool) -> float:
        if not on:
            return round_step_s()
        stop = threading.Event()

        def tick_loop():
            while not stop.wait(GOODPUT_TICK_S):
                monitor.tick()
                ticks[0] += 1

        t = threading.Thread(target=tick_loop, daemon=True,
                             name="goodput-bench-ticker")
        t.start()
        try:
            return round_step_s()
        finally:
            stop.set()
            t.join(timeout=10)

    step()                                  # warm both planes
    deltas, offs = [], []
    for r in range(GOODPUT_ROUNDS):
        if r % 2 == 0:
            off = meas(False)
            on = meas(True)
        else:
            on = meas(True)
            off = meas(False)
        deltas.append(on - off)
        offs.append(off)

    def med(vals):
        vals = sorted(vals)
        return vals[len(vals) // 2]

    ledger = goodput_report(head)           # proof of a live ledger
    out = {
        "step_off_us": round(med(offs) * 1e6, 2),
        "delta_us": round(med(deltas) * 1e6, 2),
        "overhead_frac": round(max(0.0, med(deltas)) / med(offs), 4),
        "monitor_ticks": ticks[0],
        "ledger_steps": ledger["steps"],
        "goodput_fraction": ledger["goodput_fraction"],
    }
    ray_tpu.shutdown()
    print(json.dumps(out))
    return out


def _goodput_bench(reps: int, check: bool) -> int:
    runs = []
    for rep in range(reps):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--goodput-bench-child"],
            env=env, capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        line = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
        if p.returncode != 0 or not line:
            print(p.stdout[-2000:], file=sys.stderr)
            print(p.stderr[-2000:], file=sys.stderr)
            raise RuntimeError("goodput-bench child failed")
        rec = json.loads(line[-1])
        runs.append(rec)
        print(f"# rep={rep} step_off={rec['step_off_us']}us "
              f"delta={rec['delta_us']}us "
              f"overhead={rec['overhead_frac']} "
              f"(ticks {rec['monitor_ticks']}, "
              f"ledger steps {rec['ledger_steps']})",
              file=sys.stderr)

    def med(key):
        vals = sorted(r[key] for r in runs)
        return vals[len(vals) // 2]

    result = {
        "method": f"{reps} subprocess reps; inside each child the health "
                  "monitor thread (100 ms cadence, 50x default) is "
                  "toggled between back-to-back round pairs, median pair "
                  "delta (drift-immune), then median across reps "
                  "(ADVICE.md)",
        "steps_per_round": GOODPUT_STEPS,
        "round_pairs_per_child": GOODPUT_ROUNDS,
        "monitor_tick_s": GOODPUT_TICK_S,
        "step_off_us": min(r["step_off_us"] for r in runs),
        "delta_us": med("delta_us"),
        "overhead_frac": med("overhead_frac"),
        "monitor_ticks_min": min(r["monitor_ticks"] for r in runs),
        "ledger_steps_min": min(r["ledger_steps"] for r in runs),
    }
    gates = {
        # the observatory acceptance gate: watching costs <= 1% of the
        # step loop it watches (at 50x the production tick cadence)
        "monitor_overhead_le_1pct": result["overhead_frac"] <= 0.01,
        # no vacuous pass: the monitor actually ticked and the ledger
        # actually folded the run's spans
        "monitor_actually_ticked": result["monitor_ticks_min"] > 0,
        "ledger_not_vacuous":
            result["ledger_steps_min"] > 0
            and all(r["goodput_fraction"] is not None for r in runs),
    }
    result["check"] = gates
    result["check_passed"] = all(gates.values())
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_GOODPUT.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    if check and not result["check_passed"]:
        print("GOODPUT BENCH CHECK FAILED", file=sys.stderr)
        return 1
    return 0


# --------------------------------------------------------------------------- #
# XLA-observatory overhead bench (BENCH_XLA.json)
#
# The compile-observatory claim: wrapping a jitted step in
# ObservedFunction (per-call aval fingerprint + dict probe, the steady
# path after the first compile) must cost <= 1% vs the raw jit, on the
# spmd shard_map train step loop it actually instruments. Same
# estimator as the goodput bench: back-to-back (off, on) round pairs
# with alternating order, per-pair delta, median pair per child, median
# child across subprocess reps. The OFF arm is the raw jit — exactly
# what observe_compiled returns when the observatory is disabled.
# Non-vacuous: the child forces a shape change through the observed fn
# and asserts the registry recorded the program AND counted the
# recompile, so the gate can't pass with observation accidentally off.
# The child also cross-checks the observatory's analytic MFU (XLA
# cost_analysis FLOPs over the measured spmd.compute span) against the
# bench.py 6ND+attention estimate over the SAME measured step time:
# the two FLOPs models must agree within XLA_MFU_TOLERANCE_X
# (cost_analysis counts every HLO op — remat, rngs, softmax — so it
# sits above the 6ND floor; docs/observability.md documents the bound).
# --------------------------------------------------------------------------- #

XLA_STEPS = 300           # steps per measured round
XLA_ROUNDS = 8            # back-to-back (off, on) round pairs per child
XLA_MFU_STEPS = 20        # measured spmd steps for the MFU cross-check
XLA_MFU_TOLERANCE_X = 2.5  # analytic-vs-6ND MFU agreement factor


def _xla_bench_child() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.core.config import global_config
    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.train.spmd import (
        _sp_compute,
        build_train_mesh,
        make_spmd_train_step,
    )
    from ray_tpu.util import flight_recorder
    from ray_tpu.util import xla_observatory as xo

    flight_recorder.configure(enabled=True)
    cfg = LlamaConfig.debug()
    mesh = build_train_mesh("")
    knobs = global_config()

    # -- overhead A/B on the spmd step loop: building the step with the
    # observatory disabled hands back the raw jit (the OFF arm);
    # enabled, the ObservedFunction wrapper (the ON arm) ---------------
    knobs.xla_observatory_enabled = False
    _, step_off, ds, _ = make_spmd_train_step(cfg, mesh, donate=False)
    knobs.xla_observatory_enabled = True
    init_on, step_on, _, _ = make_spmd_train_step(cfg, mesh, donate=False)

    state = init_on(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    batch, seq = 8, 33
    toks = jax.device_put(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32), ds)
    step_off(state, toks)[1].block_until_ready()   # both arms compile
    step_on(state, toks)[1].block_until_ready()    # outside the timing

    def round_step_s(fn):
        t0 = time.perf_counter()
        for _ in range(XLA_STEPS):
            fn(state, toks)[1].block_until_ready()
        return (time.perf_counter() - t0) / XLA_STEPS

    deltas, offs = [], []
    for r in range(XLA_ROUNDS):
        if r % 2 == 0:
            off = round_step_s(step_off)
            on = round_step_s(step_on)
        else:
            on = round_step_s(step_on)
            off = round_step_s(step_off)
        deltas.append(on - off)
        offs.append(off)

    def med(vals):
        vals = sorted(vals)
        return vals[len(vals) // 2]

    # -- anti-cheat: a shape change must surface as a counted recompile
    observed = xo.observe_compiled(jax.jit(lambda m: m @ m),
                                   "xla.bench_step")
    observed(jnp.zeros((512, 512), jnp.float32)).block_until_ready()
    observed(jnp.zeros((256, 256), jnp.float32)).block_until_ready()
    bench_rec = xo.snapshot().get("xla.bench_step", {})

    # -- MFU agreement: analytic (cost_analysis / measured span) vs the
    # bench.py 6ND+attn formula over the SAME measured step time -------
    for _ in range(XLA_MFU_STEPS):
        t0 = flight_recorder.now()
        _, loss = step_on(state, toks)
        loss.block_until_ready()
        _sp_compute.end(t0)

    report = xo.xla_report(None)
    row = report["programs"].get("spmd.train_step", {})
    mfu_analytic = row.get("mfu")
    mean_step_s = row.get("mean_step_s") or 0.0
    mfu_bench = None
    if mean_step_s > 0:
        tok_s = batch * seq / mean_step_s
        model_flops = 6.0 * cfg.num_params() * tok_s
        attn_flops = (6.0 * cfg.n_layers * cfg.n_heads * seq
                      * cfg.head_dim * tok_s)
        peak = xo.peak_flops_per_chip() * jax.device_count()
        mfu_bench = (model_flops + attn_flops) / peak

    out = {
        "step_off_us": round(med(offs) * 1e6, 2),
        "delta_us": round(med(deltas) * 1e6, 2),
        "overhead_frac": round(max(0.0, med(deltas)) / med(offs), 4),
        "programs": len(report["programs"]),
        "bench_step_compiles": int(bench_rec.get("compiles", 0)),
        "bench_step_recompiles": int(bench_rec.get("recompiles", 0)),
        "mfu_analytic": mfu_analytic,
        "mfu_bench_formula": (round(mfu_bench, 6)
                              if mfu_bench is not None else None),
        "mfu_ratio": (round(mfu_analytic / mfu_bench, 4)
                      if mfu_analytic and mfu_bench else None),
    }
    print(json.dumps(out))
    return out


def _xla_bench(reps: int, check: bool) -> int:
    runs = []
    for rep in range(reps):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--xla-bench-child"],
            env=env, capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        line = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
        if p.returncode != 0 or not line:
            print(p.stdout[-2000:], file=sys.stderr)
            print(p.stderr[-2000:], file=sys.stderr)
            raise RuntimeError("xla-bench child failed")
        rec = json.loads(line[-1])
        runs.append(rec)
        print(f"# rep={rep} step_off={rec['step_off_us']}us "
              f"delta={rec['delta_us']}us "
              f"overhead={rec['overhead_frac']} "
              f"(programs {rec['programs']}, "
              f"recompiles {rec['bench_step_recompiles']}, "
              f"mfu_ratio {rec['mfu_ratio']})",
              file=sys.stderr)

    def med(key):
        vals = sorted(r[key] for r in runs)
        return vals[len(vals) // 2]

    tol = XLA_MFU_TOLERANCE_X
    ratios = [r["mfu_ratio"] for r in runs]
    result = {
        "method": f"{reps} subprocess reps; inside each child the "
                  "ObservedFunction wrapper is measured against the raw "
                  "jit it wraps over back-to-back round pairs with "
                  "alternating order, median pair delta (drift-immune), "
                  "then median across reps (ADVICE.md)",
        "steps_per_round": XLA_STEPS,
        "round_pairs_per_child": XLA_ROUNDS,
        "step_off_us": min(r["step_off_us"] for r in runs),
        "delta_us": med("delta_us"),
        "overhead_frac": med("overhead_frac"),
        "programs_min": min(r["programs"] for r in runs),
        "recompiles_min": min(r["bench_step_recompiles"] for r in runs),
        "mfu_analytic": med("mfu_analytic"),
        "mfu_bench_formula": med("mfu_bench_formula"),
        "mfu_ratios": ratios,
        "mfu_tolerance_x": tol,
    }
    gates = {
        # the observatory acceptance gate: observation costs <= 1% of
        # the jitted step it observes
        "observe_overhead_le_1pct": result["overhead_frac"] <= 0.01,
        # no vacuous pass: the registry actually saw programs and the
        # forced shape change was counted as a recompile
        "registry_saw_programs": result["programs_min"] >= 1,
        "recompile_counter_exercised": result["recompiles_min"] >= 1,
        # the two FLOPs models agree within the documented factor
        "mfu_agreement": all(
            r is not None and (1.0 / tol) <= r <= tol for r in ratios),
    }
    result["check"] = gates
    result["check_passed"] = all(gates.values())
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_XLA.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    if check and not result["check_passed"]:
        print("XLA BENCH CHECK FAILED", file=sys.stderr)
        return 1
    return 0


# --------------------------------------------------------------------------- #
# Fault-tolerance bench (BENCH_FT.json)
#
# Steady direct actor traffic against a daemon-hosted actor while the head
# is BOUNCED mid-run (Head.bounce(): listener + daemon links die, durable
# tables reload, daemons re-register with replay). Measures the p99 blip
# the control-plane restart causes on the data plane, verifies the daemon
# rejoins within the grace, and asserts ZERO lost objects: every object
# sealed before the bounce (driver store + daemon store) must still
# resolve afterwards. Methodology per ADVICE.md: subprocess per rep,
# min-of-rounds for the latency numbers, worst-of-rounds for the gates.
#
# Second drill (same BENCH_FT.json): the TTRT chaos ramp. An SPMD-style
# step loop feeds from a restartable ingest actor while the daemon
# HOSTING that actor is SIGKILLed mid-run; the actor fails over to the
# surviving daemon (max_restarts) and the in-flight batch replays
# (max_task_retries). The goodput observatory must measure the whole
# story on its own: the death event opens a TTRT record against the
# pre-fault throughput baseline, the record closes when tokens/s is
# back within ttrt_recovery_fraction, and the ledger attributes the
# outage as recovery badput. Gates: TTRT recovered in every rep and
# bounded, recovery badput attributed.
# --------------------------------------------------------------------------- #

FT_WARM_CALLS = 30
FT_WINDOW_S = 3.0       # steady window measured before the bounce
FT_BLIP_WINDOW_S = 3.0  # window the bounce lands in


def _chaos_bench_child() -> dict:
    import tempfile
    import threading

    import numpy as np

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    storage = tempfile.mkdtemp(prefix="raytpu_ftbench_")
    cluster = Cluster(head_node_args={"num_cpus": 2, "storage": storage})
    cluster.add_node(num_cpus=2, resources={"far": 2},
                     separate_process=True)
    head = cluster.head
    daemon_hexes = {h for h, n in head.nodes.items()
                    if not hasattr(n, "store")}

    @ray_tpu.remote(resources={"far": 1})
    class A:
        def m(self, x):
            return x

    @ray_tpu.remote(resources={"far": 1})
    def make(tag):
        return np.full(200_000, tag, dtype=np.uint8)

    a = A.remote()
    for i in range(FT_WARM_CALLS):
        ray_tpu.get(a.m.remote(i))
    # objects that must survive: daemon-sealed task results + driver puts
    survivors = [make.remote(i) for i in range(4)]
    survivors += [ray_tpu.put(np.full(200_000, 50 + i, dtype=np.uint8))
                  for i in range(4)]
    ray_tpu.wait(survivors, num_returns=len(survivors), timeout=60,
                 fetch_local=False)

    def window(duration: float):
        lat = []
        end = time.perf_counter() + duration
        i = 0
        while time.perf_counter() < end:
            t0 = time.perf_counter()
            ray_tpu.get(a.m.remote(i))
            lat.append(time.perf_counter() - t0)
            i += 1
        return lat

    pre = window(FT_WINDOW_S)

    bounced_at = []

    def do_bounce():
        time.sleep(0.5)
        t0 = time.monotonic()
        head.bounce()
        bounced_at.append(t0)

    bouncer = threading.Thread(target=do_bounce)
    bouncer.start()
    blip = window(FT_BLIP_WINDOW_S)
    bouncer.join()
    # rejoin time: observable state (the daemon back in head.nodes)
    rejoin_deadline = time.monotonic() + 30
    while time.monotonic() < rejoin_deadline \
            and not daemon_hexes <= set(head.nodes):
        time.sleep(0.05)
    rejoin_s = time.monotonic() - bounced_at[0]
    rejoined = daemon_hexes <= set(head.nodes)
    post = window(FT_WINDOW_S)

    lost = 0
    for idx, ref in enumerate(survivors):
        try:
            v = ray_tpu.get(ref, timeout=30)
            expect = idx if idx < 4 else 50 + (idx - 4)
            if int(v[0]) != expect or v.shape != (200_000,):
                lost += 1
        except Exception:
            lost += 1

    def p(q, xs):
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(q * len(xs)))]

    out = {
        "calls_pre": len(pre), "calls_blip": len(blip),
        "calls_post": len(post),
        "p50_pre_ms": round(p(0.50, pre) * 1e3, 3),
        "p99_pre_ms": round(p(0.99, pre) * 1e3, 3),
        "p99_blip_ms": round(p(0.99, blip) * 1e3, 3),
        "max_blip_ms": round(max(blip) * 1e3, 3),
        "p99_post_ms": round(p(0.99, post) * 1e3, 3),
        "rejoin_s": round(rejoin_s, 2),
        "rejoined": rejoined,
        "objects_lost": lost,
    }
    cluster.shutdown()
    print(json.dumps(out))
    return out


FT_TTRT_PRE_S = 2.5      # steady steps before the kill
FT_TTRT_TOKENS = 1024    # tokens per step (fixed: rate = tokens/dt)
FT_TTRT_DEADLINE_S = 90  # ramp abandons if throughput never recovers


def _chaos_ttrt_child() -> dict:
    import signal as _signal

    import jax
    import jax.numpy as jnp

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.core.config import global_config
    from ray_tpu.train.spmd import _g_tokens_per_sec, _sp_compute
    from ray_tpu.util import flight_recorder
    from ray_tpu.util.goodput import goodput_report
    from ray_tpu.util.metrics import registry

    cfg = global_config()
    cfg.flight_recorder_report_interval_ms = 300
    cfg.health_check_period_ms = 300        # fast fault detection
    cfg.health_monitor_interval_ms = 3_600_000   # the ramp drives ticks
    cfg.metrics_history_interval_ms = 3_600_000  # ...and the sampling
    cluster = Cluster(head_node_args={"num_cpus": 1})
    # both daemons carry the ingest resource so the failover has a home
    cluster.add_node(num_cpus=1, resources={"ftpool": 2},
                     separate_process=True)
    cluster.add_node(num_cpus=1, resources={"ftpool": 2},
                     separate_process=True)
    head = cluster.head
    monitor = head.health_monitor

    @ray_tpu.remote(resources={"ftpool": 1}, max_restarts=1,
                    max_task_retries=1)
    class BenchIngest:
        def batch(self, i):
            return i

    ingest = BenchIngest.remote()
    ray_tpu.get(ingest.batch.remote(0), timeout=60)
    # ground truth for the kill: which daemon hosts the ingest actor
    # (class_name is qualified, e.g. "BenchIngest.__init__")
    host_hex = next(a["node_hex"]
                    for a in head.state_list("actors")
                    if "BenchIngest" in str(a["class_name"])
                    and a["node_hex"])
    victim = next(n for n in head.nodes.values() if n.hex == host_hex)

    k = jax.jit(lambda m: m @ m)
    x = jnp.zeros((256, 256), jnp.float32)
    k(x).block_until_ready()

    last_tick = [0.0]

    def step(i):
        """One SPMD-style step: ingest fetch + compute span + the
        throughput sample the TTRT tracker watches."""
        t_wall = time.perf_counter()
        ray_tpu.get(ingest.batch.remote(i), timeout=FT_TTRT_DEADLINE_S)
        t0 = flight_recorder.now()
        k(x).block_until_ready()
        _sp_compute.end(t0)
        dt = max(time.perf_counter() - t_wall, 1e-9)
        _g_tokens_per_sec.set(FT_TTRT_TOKENS / dt, tags={"loop": "spmd"})
        head.metrics_history.sample(registry(), now=time.time())
        if time.monotonic() - last_tick[0] > 0.25:
            last_tick[0] = time.monotonic()
            monitor.tick()
        return dt

    i, end = 0, time.monotonic() + FT_TTRT_PRE_S
    while time.monotonic() < end:
        step(i)
        i += 1
    pre_steps = i

    os.kill(victim.pid, _signal.SIGKILL)
    killed_at = time.monotonic()
    blip_s = 0.0
    deadline = time.monotonic() + FT_TTRT_DEADLINE_S
    recovered = None
    while time.monotonic() < deadline and recovered is None:
        blip_s = max(blip_s, step(i))
        i += 1
        recovered = next((r for r in monitor.ttrt.summary()
                          if r["recovered_ts"] is not None), None)
    monitor.tick()
    ledger = goodput_report(head)
    out = {
        "pre_steps": pre_steps,
        "post_steps": i - pre_steps,
        "blip_s": round(blip_s, 3),
        "wall_after_kill_s": round(time.monotonic() - killed_at, 3),
        "ttrt_recovered": recovered is not None,
        "ttrt_s": recovered["ttrt_s"] if recovered else None,
        "ttrt_baseline": round(recovered["baseline"], 1)
        if recovered else None,
        "recovery_badput_s": ledger["badput_s"]["recovery"],
        "recovery_gap_entities":
            sorted({g["entity"] for g in ledger.get("recovery_gaps", ())}),
        "victim": victim.hex[:8],
    }
    cluster.shutdown()
    print(json.dumps(out))
    return out


def _chaos_bench(reps: int, check: bool) -> int:
    runs = []
    for rep in range(reps):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--chaos-bench-child"],
            env=env, capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        line = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
        if p.returncode != 0 or not line:
            print(p.stdout[-2000:], file=sys.stderr)
            print(p.stderr[-2000:], file=sys.stderr)
            raise RuntimeError("chaos-bench child failed")
        rec = json.loads(line[-1])
        runs.append(rec)
        print(f"# rep={rep} p99_pre={rec['p99_pre_ms']}ms "
              f"p99_blip={rec['p99_blip_ms']}ms "
              f"p99_post={rec['p99_post_ms']}ms "
              f"rejoin={rec['rejoin_s']}s lost={rec['objects_lost']}",
              file=sys.stderr)

    ttrt_runs = []
    for rep in range(reps):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--chaos-ttrt-child"],
            env=env, capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        line = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
        if p.returncode != 0 or not line:
            print(p.stdout[-2000:], file=sys.stderr)
            print(p.stderr[-2000:], file=sys.stderr)
            raise RuntimeError("chaos-ttrt child failed")
        rec = json.loads(line[-1])
        ttrt_runs.append(rec)
        print(f"# ttrt rep={rep} recovered={rec['ttrt_recovered']} "
              f"ttrt={rec['ttrt_s']}s blip={rec['blip_s']}s "
              f"recovery_badput={rec['recovery_badput_s']}s",
              file=sys.stderr)

    result = {
        "method": f"{reps} subprocess reps; latency = min-of-rounds, "
                  "gates = worst-of-rounds (ADVICE.md)",
        "p99_pre_ms": min(r["p99_pre_ms"] for r in runs),
        "p99_blip_ms": min(r["p99_blip_ms"] for r in runs),
        "max_blip_ms": min(r["max_blip_ms"] for r in runs),
        "p99_post_ms": min(r["p99_post_ms"] for r in runs),
        "rejoin_s_worst": max(r["rejoin_s"] for r in runs),
        "objects_lost_total": sum(r["objects_lost"] for r in runs),
        "ttrt_s_worst": max((r["ttrt_s"] for r in ttrt_runs
                             if r["ttrt_s"] is not None), default=None),
        "ttrt_runs": ttrt_runs,
        "runs": runs,
    }
    result["blip_ratio"] = round(
        result["p99_blip_ms"] / max(result["p99_pre_ms"], 1e-9), 2)
    result["post_recovery_ratio"] = round(
        result["p99_post_ms"] / max(result["p99_pre_ms"], 1e-9), 2)
    gates = {
        # the whole point: a control-plane restart loses NOTHING
        "objects_lost_zero": result["objects_lost_total"] == 0,
        "daemon_rejoined_all_reps": all(r["rejoined"] for r in runs),
        # blip bounded: the direct plane rides peer channels, so even
        # during the bounce no call may stall past 2 s (worst rep)
        "blip_bounded_2s": max(r["max_blip_ms"] for r in runs) <= 2000.0,
        # steady state fully recovers (min-of-rounds, 3x headroom for the
        # 1-core box's scheduling noise)
        "post_p99_within_3x": result["post_recovery_ratio"] <= 3.0,
        # the TTRT ramp: every rep's daemon-kill measured a closed
        # time-to-recovered-throughput, bounded (detection 300 ms +
        # actor failover; 30 s is ample even on a loaded 1-core box)
        "ttrt_recovered_all_reps":
            all(r["ttrt_recovered"] for r in ttrt_runs),
        "ttrt_within_30s": all(
            r["ttrt_s"] is not None and r["ttrt_s"] <= 30.0
            for r in ttrt_runs),
        # ...and the outage shows up in the ledger as attributed
        # recovery badput against the killed node
        "recovery_badput_attributed": all(
            r["recovery_badput_s"] > 0
            and r["victim"] in r["recovery_gap_entities"]
            for r in ttrt_runs),
    }
    result["check"] = gates
    result["check_passed"] = all(gates.values())
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_FT.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    if check and not result["check_passed"]:
        print("CHAOS BENCH CHECK FAILED", file=sys.stderr)
        return 1
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default="", help="comma-separated subset")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--num-cpus", type=int, default=4)
    ap.add_argument("--daemons", type=int, default=0,
                    help="add N separate-process node daemons (direct-task "
                    "spillback topology) and run a many-tasks op across "
                    "them")
    ap.add_argument("--many", type=int, default=50_000,
                    help="task count for the many-tasks envelope probe "
                    "(--daemons runs)")
    ap.add_argument("--actor-bench", action="store_true",
                    help="head-free actor plane A/B (BENCH_ACTOR.json): "
                    "actor p50 + cross-process stream items/s with the "
                    "head slowed vs not")
    ap.add_argument("--actor-bench-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--dag-bench", action="store_true",
                    help="compiled-graph data plane (BENCH_DAG.json): "
                    "per-hop dispatch vs remote(), pipelined vs lockstep "
                    "4-stage throughput, MPMD trainer bubble fraction")
    ap.add_argument("--dag-bench-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--trace-bench", action="store_true",
                    help="flight-recorder overhead A/B (BENCH_TRACE.json): "
                    "compiled-hop p50 with the recorder on vs off, "
                    "<=3% overhead gate")
    ap.add_argument("--trace-bench-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--goodput-bench", action="store_true",
                    help="health-monitor overhead A/B (BENCH_GOODPUT.json): "
                    "spmd step loop with the monitor ticking vs off, "
                    "<=1% overhead gate")
    ap.add_argument("--goodput-bench-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--xla-bench", action="store_true",
                    help="XLA-observatory overhead A/B (BENCH_XLA.json): "
                    "ObservedFunction wrapper vs the raw jit, <=1% "
                    "overhead gate, recompile-counter anti-cheat, "
                    "analytic-vs-6ND MFU agreement")
    ap.add_argument("--xla-bench-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--chaos-bench", action="store_true",
                    help="fault-tolerance bench (BENCH_FT.json): p99 blip "
                    "across an injected head bounce under steady actor "
                    "traffic, daemon rejoin time, objects-lost==0 gate, "
                    "plus the daemon-kill TTRT ramp")
    ap.add_argument("--chaos-bench-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--chaos-ttrt-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when the actor-/dag-/trace-/goodput-/"
                    "xla-/chaos-bench gates fail")
    args = ap.parse_args()

    if args.actor_bench_child:
        _actor_bench_child()
        return {}
    if args.actor_bench:
        raise SystemExit(_actor_bench(args.reps, args.check))
    if args.dag_bench_child:
        _dag_bench_child()
        return {}
    if args.dag_bench:
        raise SystemExit(_dag_bench(args.reps, args.check))
    if args.trace_bench_child:
        _trace_bench_child()
        return {}
    if args.trace_bench:
        raise SystemExit(_trace_bench(args.reps, args.check))
    if args.goodput_bench_child:
        _goodput_bench_child()
        return {}
    if args.goodput_bench:
        raise SystemExit(_goodput_bench(args.reps, args.check))
    if args.xla_bench_child:
        _xla_bench_child()
        return {}
    if args.xla_bench:
        raise SystemExit(_xla_bench(args.reps, args.check))
    if args.chaos_bench_child:
        _chaos_bench_child()
        return {}
    if args.chaos_ttrt_child:
        _chaos_ttrt_child()
        return {}
    if args.chaos_bench:
        raise SystemExit(_chaos_bench(args.reps, args.check))

    import ray_tpu

    cluster = None
    if args.daemons:
        from ray_tpu.cluster_utils import Cluster

        cluster = Cluster(head_node_args={"num_cpus": args.num_cpus})
        for _ in range(args.daemons):
            cluster.add_node(num_cpus=args.num_cpus, separate_process=True)
    else:
        ray_tpu.init(num_cpus=args.num_cpus)
    results = {}
    selected = set(args.ops.split(",")) if args.ops else None

    def run(name, fn, multiplier=1):
        if selected and name not in selected:
            return
        results[name] = timeit(name, fn, multiplier)

    # ---- objects ----------------------------------------------------------
    small = b"x" * 1024

    def put_small():
        for _ in range(100):
            ray_tpu.put(small)

    run("put_small_1kb", put_small, 100)

    ref = ray_tpu.put(small)

    def get_small():
        for _ in range(100):
            ray_tpu.get(ref)

    run("get_small_1kb", get_small, 100)

    big = b"x" * (100 * 1024 * 1024)

    def put_100mb():
        r = ray_tpu.put(big)
        del r

    run("put_100mb", put_100mb, 1)

    bref = ray_tpu.put(big)

    def get_100mb():
        ray_tpu.get(bref)

    run("get_100mb", get_100mb, 1)

    # ---- tasks ------------------------------------------------------------
    @ray_tpu.remote
    def nop():
        return b"ok"

    ray_tpu.get(nop.remote())

    def task_sync():
        ray_tpu.get(nop.remote())

    run("task_round_trip_sync", task_sync, 1)

    def tasks_async_batch():
        ray_tpu.get([nop.remote() for _ in range(1000)])

    run("tasks_async_batch_1k", tasks_async_batch, 1000)

    @ray_tpu.remote
    def nop_arg(x):
        return x

    sref = ray_tpu.put(small)

    def tasks_with_arg():
        ray_tpu.get([nop_arg.remote(sref) for _ in range(100)])

    run("tasks_with_object_arg", tasks_with_arg, 100)

    # ---- actors -----------------------------------------------------------
    @ray_tpu.remote
    class A:
        def m(self):
            return b"ok"

        async def am(self):
            return b"ok"

    a = A.remote()
    ray_tpu.get(a.m.remote())

    def actor_sync():
        ray_tpu.get(a.m.remote())

    run("actor_call_sync", actor_sync, 1)

    def actor_async_batch():
        ray_tpu.get([a.m.remote() for _ in range(1000)])

    run("actor_calls_batch_1k", actor_async_batch, 1000)

    aa = A.options(max_concurrency=8).remote()
    ray_tpu.get(aa.am.remote())

    def async_actor_batch():
        ray_tpu.get([aa.am.remote() for _ in range(1000)])

    run("async_actor_calls_batch_1k", async_actor_batch, 1000)

    # ---- streaming generators (direct reply-chain items) ------------------
    @ray_tpu.remote
    class Gen:
        def stream(self, n):
            for i in range(n):
                yield i

    g = Gen.remote()

    def stream_items_1k():
        it = g.stream.options(num_returns="streaming").remote(1000)
        for r in it:
            pass

    run("stream_items_1k", stream_items_1k, 1000)

    def stream_items_consumed_1k():
        it = g.stream.options(num_returns="streaming").remote(1000)
        for r in it:
            ray_tpu.get(r)

    run("stream_items_consumed_1k", stream_items_consumed_1k, 1000)

    # ---- head path comparison (regression gate: the direct path must
    # beat routing every submit/finish through the head) ------------------
    from ray_tpu.core.config import global_config as _gc

    def _with_head_path(fn):
        cfg = _gc()
        cfg.direct_task_enabled = False
        cfg.direct_actor_enabled = False
        try:
            fn()
        finally:
            cfg.direct_task_enabled = True
            cfg.direct_actor_enabled = True

    def headpath_tasks_batch():
        _with_head_path(
            lambda: ray_tpu.get([nop.remote() for _ in range(1000)]))

    run("headpath_tasks_batch_1k", headpath_tasks_batch, 1000)

    def headpath_actor_batch():
        _with_head_path(
            lambda: ray_tpu.get([a.m.remote() for _ in range(1000)]))

    run("headpath_actor_calls_1k", headpath_actor_batch, 1000)

    # ---- wait -------------------------------------------------------------
    def wait_one():
        refs = [nop.remote() for _ in range(10)]
        ray_tpu.wait(refs, num_returns=1)
        ray_tpu.get(refs)

    run("wait_first_of_10", wait_one, 10)

    if args.daemons:
        # scalability-envelope probe (reference: release/benchmarks
        # distributed/test_many_tasks.py): direct path + spillback across
        # the daemons; the head sees only batched events. The driver
        # process's CPU time per task is the head-flatness evidence: on
        # the direct path the head does no per-task work, so cpu/task
        # must stay flat as the count scales.
        import resource

        from ray_tpu.core import runtime as _rt

        n = args.many

        def cpu_s() -> float:
            ru = resource.getrusage(resource.RUSAGE_SELF)
            return ru.ru_utime + ru.ru_stime

        # chunked submission keeps driver memory bounded at envelope scale
        def many_tasks():
            chunk = 5000
            for start in range(0, n, chunk):
                ray_tpu.get([nop.remote() for _ in
                             range(min(chunk, n - start))], timeout=600)

        c0, t0 = cpu_s(), time.perf_counter()
        many_tasks()
        dt = time.perf_counter() - t0
        dcpu = cpu_s() - c0
        rate = n / dt
        cpu_us = dcpu / n * 1e6
        results[f"many_tasks_{n}_across_daemons"] = rate
        results["many_tasks_driver_cpu_us_per_task"] = cpu_us
        print(f"{'many_tasks_%d_across_daemons' % n:<42s} {rate:>12.1f} /s")
        print(f"{'many_tasks_driver_cpu_us_per_task':<42s} {cpu_us:>12.1f} us")

        head = _rt.get_current_runtime().head
        results["head_task_records_after_bench"] = len(head.tasks)
        print(f"# head.tasks after all ops: {len(head.tasks)} "
              f"(direct task+actor paths leave no per-call head records)")

    if cluster is not None:
        cluster.shutdown()
    else:
        ray_tpu.shutdown()
    if args.json:
        print(json.dumps(results))
    return results


if __name__ == "__main__":
    main()
