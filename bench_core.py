"""Core runtime microbenchmarks.

Port of the reference's microbenchmark op set
(/root/reference/python/ray/_private/ray_perf.py:120-315): put/get rates,
task submit/round-trip rates, actor call rates, wait. Run:

    python bench_core.py [--ops op1,op2] [--json]

Prints one line per op; with --json, a JSON object of all results. These
are the regression gates for the control/object planes (the tensor plane is
bench.py's job).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def timeit(name, fn, multiplier=1, warmup=1, min_time=1.0):
    for _ in range(warmup):
        fn()
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < min_time:
        fn()
        count += 1
    dt = time.perf_counter() - start
    rate = count * multiplier / dt
    print(f"{name:<42s} {rate:>12.1f} /s")
    return rate


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default="", help="comma-separated subset")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--num-cpus", type=int, default=4)
    ap.add_argument("--daemons", type=int, default=0,
                    help="add N separate-process node daemons (direct-task "
                    "spillback topology) and run a many-tasks op across "
                    "them")
    ap.add_argument("--many", type=int, default=50_000,
                    help="task count for the many-tasks envelope probe "
                    "(--daemons runs)")
    args = ap.parse_args()

    import ray_tpu

    cluster = None
    if args.daemons:
        from ray_tpu.cluster_utils import Cluster

        cluster = Cluster(head_node_args={"num_cpus": args.num_cpus})
        for _ in range(args.daemons):
            cluster.add_node(num_cpus=args.num_cpus, separate_process=True)
    else:
        ray_tpu.init(num_cpus=args.num_cpus)
    results = {}
    selected = set(args.ops.split(",")) if args.ops else None

    def run(name, fn, multiplier=1):
        if selected and name not in selected:
            return
        results[name] = timeit(name, fn, multiplier)

    # ---- objects ----------------------------------------------------------
    small = b"x" * 1024

    def put_small():
        for _ in range(100):
            ray_tpu.put(small)

    run("put_small_1kb", put_small, 100)

    ref = ray_tpu.put(small)

    def get_small():
        for _ in range(100):
            ray_tpu.get(ref)

    run("get_small_1kb", get_small, 100)

    big = b"x" * (100 * 1024 * 1024)

    def put_100mb():
        r = ray_tpu.put(big)
        del r

    run("put_100mb", put_100mb, 1)

    bref = ray_tpu.put(big)

    def get_100mb():
        ray_tpu.get(bref)

    run("get_100mb", get_100mb, 1)

    # ---- tasks ------------------------------------------------------------
    @ray_tpu.remote
    def nop():
        return b"ok"

    ray_tpu.get(nop.remote())

    def task_sync():
        ray_tpu.get(nop.remote())

    run("task_round_trip_sync", task_sync, 1)

    def tasks_async_batch():
        ray_tpu.get([nop.remote() for _ in range(1000)])

    run("tasks_async_batch_1k", tasks_async_batch, 1000)

    @ray_tpu.remote
    def nop_arg(x):
        return x

    sref = ray_tpu.put(small)

    def tasks_with_arg():
        ray_tpu.get([nop_arg.remote(sref) for _ in range(100)])

    run("tasks_with_object_arg", tasks_with_arg, 100)

    # ---- actors -----------------------------------------------------------
    @ray_tpu.remote
    class A:
        def m(self):
            return b"ok"

        async def am(self):
            return b"ok"

    a = A.remote()
    ray_tpu.get(a.m.remote())

    def actor_sync():
        ray_tpu.get(a.m.remote())

    run("actor_call_sync", actor_sync, 1)

    def actor_async_batch():
        ray_tpu.get([a.m.remote() for _ in range(1000)])

    run("actor_calls_batch_1k", actor_async_batch, 1000)

    aa = A.options(max_concurrency=8).remote()
    ray_tpu.get(aa.am.remote())

    def async_actor_batch():
        ray_tpu.get([aa.am.remote() for _ in range(1000)])

    run("async_actor_calls_batch_1k", async_actor_batch, 1000)

    # ---- streaming generators (direct reply-chain items) ------------------
    @ray_tpu.remote
    class Gen:
        def stream(self, n):
            for i in range(n):
                yield i

    g = Gen.remote()

    def stream_items_1k():
        it = g.stream.options(num_returns="streaming").remote(1000)
        for r in it:
            pass

    run("stream_items_1k", stream_items_1k, 1000)

    def stream_items_consumed_1k():
        it = g.stream.options(num_returns="streaming").remote(1000)
        for r in it:
            ray_tpu.get(r)

    run("stream_items_consumed_1k", stream_items_consumed_1k, 1000)

    # ---- head path comparison (regression gate: the direct path must
    # beat routing every submit/finish through the head) ------------------
    from ray_tpu.core.config import global_config as _gc

    def _with_head_path(fn):
        cfg = _gc()
        cfg.direct_task_enabled = False
        cfg.direct_actor_enabled = False
        try:
            fn()
        finally:
            cfg.direct_task_enabled = True
            cfg.direct_actor_enabled = True

    def headpath_tasks_batch():
        _with_head_path(
            lambda: ray_tpu.get([nop.remote() for _ in range(1000)]))

    run("headpath_tasks_batch_1k", headpath_tasks_batch, 1000)

    def headpath_actor_batch():
        _with_head_path(
            lambda: ray_tpu.get([a.m.remote() for _ in range(1000)]))

    run("headpath_actor_calls_1k", headpath_actor_batch, 1000)

    # ---- wait -------------------------------------------------------------
    def wait_one():
        refs = [nop.remote() for _ in range(10)]
        ray_tpu.wait(refs, num_returns=1)
        ray_tpu.get(refs)

    run("wait_first_of_10", wait_one, 10)

    if args.daemons:
        # scalability-envelope probe (reference: release/benchmarks
        # distributed/test_many_tasks.py): direct path + spillback across
        # the daemons; the head sees only batched events. The driver
        # process's CPU time per task is the head-flatness evidence: on
        # the direct path the head does no per-task work, so cpu/task
        # must stay flat as the count scales.
        import resource

        from ray_tpu.core import runtime as _rt

        n = args.many

        def cpu_s() -> float:
            ru = resource.getrusage(resource.RUSAGE_SELF)
            return ru.ru_utime + ru.ru_stime

        # chunked submission keeps driver memory bounded at envelope scale
        def many_tasks():
            chunk = 5000
            for start in range(0, n, chunk):
                ray_tpu.get([nop.remote() for _ in
                             range(min(chunk, n - start))], timeout=600)

        c0, t0 = cpu_s(), time.perf_counter()
        many_tasks()
        dt = time.perf_counter() - t0
        dcpu = cpu_s() - c0
        rate = n / dt
        cpu_us = dcpu / n * 1e6
        results[f"many_tasks_{n}_across_daemons"] = rate
        results["many_tasks_driver_cpu_us_per_task"] = cpu_us
        print(f"{'many_tasks_%d_across_daemons' % n:<42s} {rate:>12.1f} /s")
        print(f"{'many_tasks_driver_cpu_us_per_task':<42s} {cpu_us:>12.1f} us")

        head = _rt.get_current_runtime().head
        results["head_task_records_after_bench"] = len(head.tasks)
        print(f"# head.tasks after all ops: {len(head.tasks)} "
              f"(direct task+actor paths leave no per-call head records)")

    if cluster is not None:
        cluster.shutdown()
    else:
        ray_tpu.shutdown()
    if args.json:
        print(json.dumps(results))
    return results


if __name__ == "__main__":
    main()
