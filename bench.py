"""Headline benchmark: flagship-model training throughput on this chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is measured MFU / 0.35 — the BASELINE.md north-star target
(>=35% MFU via GSPMD). The reference publishes no model-level tokens/sec
numbers (BASELINE.json "published": {}), so the MFU target is the baseline.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


# --------------------------------------------------------------------------- #
# Remote-compile resilience (BENCH_r04 flagship failure):
# the axon platform compiles through an HTTP endpoint
# (http://127.0.0.1:<port>/remote_compile) whose tpu_compile_helper runs
# as a subprocess. BENCH_r04 recorded the flagship (1B) pass dying with
# "HTTP 500: tpu_compile_helper subprocess exit code 1" — the helper hit
# the big compile right after the bench pass, with the previous config's
# compiled executables and donated buffers still resident. Such failures
# are transient (server-side subprocess, not our program): drop our
# caches, give the helper a beat, and retry before falling down the
# config ladder.
# --------------------------------------------------------------------------- #


def is_transient_compile_error(exc: BaseException) -> bool:
    """True for failures of the remote-compile endpoint itself (HTTP 5xx
    / helper-subprocess death / connection loss) — retriable — as
    opposed to compile errors in our program, which are not."""
    msg = f"{type(exc).__name__}: {exc}"
    if "remote_compile" not in msg and "tpu_compile_helper" not in msg:
        return False
    return ("HTTP 5" in msg or "subprocess exit code" in msg
            or "Connection" in msg or "connection" in msg)


def _compile_cleanup() -> None:
    """Free what we can between attempts: dead Python refs (donated
    buffers die with them) and jax's compiled-executable caches."""
    import gc

    gc.collect()
    try:
        import jax

        jax.clear_caches()
    except Exception:
        pass


def run_with_compile_retries(fn, attempts: int = 3, cleanup=_compile_cleanup,
                             sleep=time.sleep):
    """Run ``fn`` retrying transient remote-compile endpoint failures
    with cleanup + backoff between attempts; non-transient errors (and
    the final transient one) propagate."""
    for attempt in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — classified below
            if not is_transient_compile_error(e) or attempt == attempts - 1:
                raise
            print(f"# transient remote-compile failure "
                  f"(attempt {attempt + 1}/{attempts}): "
                  f"{type(e).__name__}: {e}"[:300], file=sys.stderr)
            if cleanup is not None:
                cleanup()
            sleep(2.0 * (attempt + 1))


def peak_flops_per_chip() -> float:
    """bf16 peak FLOPs of the local accelerator."""
    env = os.environ.get("RAY_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    import jax

    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    table = {
        "tpu v5 lite": 197e12,   # v5e
        "tpu v5e": 197e12,
        "tpu v5": 459e12,        # v5p
        "tpu v4": 275e12,
        "tpu v6 lite": 918e12,   # v6e (Trillium)
    }
    for k, v in table.items():
        if k in kind:
            return v
    return 197e12 if d.platform == "tpu" else 1e12  # CPU: nominal


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="tiny config for CPU smoke-testing")
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--batch", type=int, default=0)
    parser.add_argument("--seq", type=int, default=0)
    parser.add_argument("--config", default="bench",
                        choices=["debug", "small", "medium", "bench",
                                 "flagship"])
    parser.add_argument("--no-flagship", action="store_true",
                        help="skip the flagship (1B, bf16-mu adam) pass "
                        "that normally runs alongside the bench config "
                        "on TPU")
    parser.add_argument("--devices", type=int, default=0,
                        help="run on N virtual CPU devices (re-execs with "
                        "xla_force_host_platform_device_count=N) to measure "
                        "the multi-chip GSPMD step; 0 = local devices")
    parser.add_argument("--mesh", default="",
                        help="axis spec for --devices runs, e.g. "
                        "'fsdp=2,seq=2,tensor=2' (default fsdp=N)")
    args = parser.parse_args()

    if args.devices and os.environ.get("_RAY_TPU_BENCH_CHILD") != "1":
        import subprocess

        env = dict(os.environ)
        env["_RAY_TPU_BENCH_CHILD"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(
            f"--xla_force_host_platform_device_count={args.devices}")
        env["XLA_FLAGS"] = " ".join(flags)
        argv = [os.path.abspath(sys.argv[0])] + sys.argv[1:]
        raise SystemExit(subprocess.run(
            [sys.executable] + argv, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__))).returncode)

    import jax
    import numpy as np

    from ray_tpu.models.llama import LlamaConfig, make_train_step
    from ray_tpu.parallel import MeshConfig, make_mesh

    n_dev = len(jax.devices())
    on_cpu = args.quick or jax.devices()[0].platform == "cpu"
    if on_cpu:
        # CPU (incl. --devices virtual mesh): debug config unless the user
        # explicitly picked one small enough to step on host
        cfg = (LlamaConfig.debug() if args.config == "bench"
               else getattr(LlamaConfig, args.config)())
        batch, seq, steps = 8, 128, max(3, args.steps // 4)
    else:
        cfg = getattr(LlamaConfig, args.config)()
        batch = {"medium": 8, "bench": 8, "flagship": 8}.get(args.config, 16)
        seq, steps = 2048, args.steps
    if args.batch:
        batch = args.batch
    if args.seq:
        seq = args.seq

    # single-host mesh over all local chips: fsdp over chips (or --mesh spec)
    axes = {"data": 1, "fsdp": n_dev, "seq": 1, "tensor": 1}
    if args.mesh:
        axes = {"data": 1, "fsdp": 1, "seq": 1, "tensor": 1}
        for part in args.mesh.split(","):
            k, v = part.split("=")
            axes[k.strip()] = int(v)
    mesh = make_mesh(MeshConfig(**axes))
    n_dev = mesh.size  # per-chip metrics count only devices in the mesh

    def run_config(cfg, batch, seq, steps, flagship=False):
        """Measure one training config; returns the metrics dict."""
        optimizer = None
        if flagship:
            import optax

            # adafactor, bf16 momentum: the T5/PaLM TPU recipe. Peak HBM
            # = fp32 params (4 B) + fp32 grads (4 B) + bf16 momentum (2 B)
            # + factored second moment (~0) ~= 10 B/param; the bf16-mu
            # adamw variant peaks at 14 B/param (fp32 nu + grads) and
            # OOMs the 16 GB chip above ~950M params.
            optimizer = optax.adafactor(
                learning_rate=3e-4, momentum=0.9,
                dtype_momentum=jax.numpy.bfloat16)
        init, step, data_sharding, _ = make_train_step(
            cfg, mesh, optimizer=optimizer)
        state = init(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        tokens = jax.device_put(
            rng.randint(0, cfg.vocab_size,
                        (batch, seq + 1)).astype(np.int32),
            data_sharding)
        # warmup (compile) then timed steps. NOTE: sync via host fetch —
        # block_until_ready is a no-op on the experimental axon platform.
        for _ in range(3):
            state, loss = step(state, tokens)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss = step(state, tokens)
        final_loss = float(loss)
        dt = time.perf_counter() - t0

        tokens_per_sec = batch * seq * steps / dt
        n_params = cfg.num_params()
        model_flops = 6.0 * n_params * tokens_per_sec  # fwd+bwd matmuls
        # causal attention matmul FLOPs: fwd 2*(QK^T)+2*(PV) halved by
        # causality = 2*H*T*D per token, tripled for bwd (dq + dkv)
        attn_flops = (6.0 * cfg.n_layers * cfg.n_heads * seq * cfg.head_dim
                      * tokens_per_sec)
        peak = peak_flops_per_chip() * n_dev
        mfu = model_flops / peak  # conservative: params-only numerator
        mfu_attn = (model_flops + attn_flops) / peak
        print(f"# cfg={cfg.dim}d/{cfg.n_layers}L "
              f"params={n_params/1e6:.1f}M batch={batch} seq={seq} "
              f"steps={steps} dt={dt:.2f}s mfu={mfu:.3f} "
              f"mfu_with_attn={mfu_attn:.3f} loss={final_loss:.3f} "
              f"devices={n_dev}", file=sys.stderr)
        return {
            "params_m": round(n_params / 1e6, 1),
            "tokens_per_sec_per_chip": round(tokens_per_sec / n_dev, 2),
            "mfu": round(mfu, 4),
            "mfu_with_attn": round(mfu_attn, 4),
            "vs_baseline": round(mfu / 0.35, 4),
        }

    primary = run_config(cfg, batch, seq, steps,
                         flagship=(args.config == "flagship"))
    out = {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": primary["tokens_per_sec_per_chip"],
        "unit": "tokens/s/chip",
        "vs_baseline": primary["vs_baseline"],
    }
    # the flagship pass (1B, the largest single-v5e-chip config) rides
    # along on real hardware: BENCH_r{N} then carries both the 664M trend
    # line and the flagship MFU (round-4 VERDICT ask #10)
    if (not on_cpu and args.config == "bench" and not args.no_flagship
            and not args.batch and not args.seq):
        # fallback ladder: full 1.04B, then the largest config that fits
        # with the heavier bf16-mu adamw state (2048d/14L, 924M) — the
        # committed artifact must carry a live flagship number even if
        # the compile environment regresses (round-4 VERDICT ask #2)
        ladder = [
            ("flagship_1040m", LlamaConfig.flagship()),
            ("fallback_924m", LlamaConfig(
                vocab_size=32000, dim=2048, n_layers=14, n_heads=16,
                n_kv_heads=8, mlp_dim=7168, max_seq_len=2048)),
        ]
        errors = []
        # the bench pass's compiled executables/buffers must not crowd
        # the flagship compile (BENCH_r04: helper subprocess exit 1)
        _compile_cleanup()
        for name, fcfg in ladder:
            try:
                out["flagship"] = run_with_compile_retries(
                    lambda fcfg=fcfg: run_config(fcfg, 8, 2048,
                                                 max(5, args.steps // 2),
                                                 flagship=True))
                out["flagship"]["config"] = name
                break
            except Exception as e:  # noqa: BLE001 — never lose the headline
                errors.append(f"{name}: {type(e).__name__}: {e}"[:200])
        else:
            out["flagship"] = {"error": " | ".join(errors)[:400]}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
