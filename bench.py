"""Headline benchmark: flagship-model training throughput on this chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is measured MFU / 0.35 — the BASELINE.md north-star target
(>=35% MFU via GSPMD). The reference publishes no model-level tokens/sec
numbers (BASELINE.json "published": {}), so the MFU target is the baseline.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


# --------------------------------------------------------------------------- #
# Remote-compile resilience (BENCH_r04 flagship failure):
# the axon platform compiles through an HTTP endpoint
# (http://127.0.0.1:<port>/remote_compile) whose tpu_compile_helper runs
# as a subprocess. BENCH_r04 recorded the flagship (1B) pass dying with
# "HTTP 500: tpu_compile_helper subprocess exit code 1" — the helper hit
# the big compile right after the bench pass, with the previous config's
# compiled executables and donated buffers still resident. Such failures
# are transient (server-side subprocess, not our program): drop our
# caches, give the helper a beat, and retry before falling down the
# config ladder.
# --------------------------------------------------------------------------- #


def is_transient_compile_error(exc: BaseException) -> bool:
    """True for failures of the remote-compile endpoint itself (HTTP 5xx
    / helper-subprocess death / connection loss) — retriable — as
    opposed to compile errors in our program, which are not."""
    msg = f"{type(exc).__name__}: {exc}"
    if "remote_compile" not in msg and "tpu_compile_helper" not in msg:
        return False
    return ("HTTP 5" in msg or "subprocess exit code" in msg
            or "Connection" in msg or "connection" in msg)


def _compile_cleanup() -> None:
    """Free what we can between attempts: dead Python refs (donated
    buffers die with them) and jax's compiled-executable caches."""
    import gc

    gc.collect()
    try:
        import jax

        jax.clear_caches()
    except Exception:
        pass


def run_with_compile_retries(fn, attempts: int = 3, cleanup=_compile_cleanup,
                             sleep=time.sleep):
    """Run ``fn`` retrying transient remote-compile endpoint failures
    with cleanup + backoff between attempts; non-transient errors (and
    the final transient one) propagate."""
    for attempt in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — classified below
            if not is_transient_compile_error(e) or attempt == attempts - 1:
                raise
            print(f"# transient remote-compile failure "
                  f"(attempt {attempt + 1}/{attempts}): "
                  f"{type(e).__name__}: {e}"[:300], file=sys.stderr)
            if cleanup is not None:
                cleanup()
            sleep(2.0 * (attempt + 1))


def peak_flops_per_chip() -> float:
    """bf16 peak FLOPs of the local accelerator (the observatory's table
    — one source of truth with the /api/xla roofline). The historical
    ``RAY_TPU_PEAK_FLOPS`` env override still wins; ``xla_peak_flops``
    in Config is the knob the rest of the tree uses."""
    env = os.environ.get("RAY_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    from ray_tpu.util.xla_observatory import peak_flops_per_chip as peak

    return peak()


def measure_sharded(cfg, mesh, batch, seq, steps, donate=True,
                    gspmd_parity=False, gather="streamed"):
    """One sharded-train measurement (train/spmd.py shard_map step):
    tokens/s/chip, MFU, and the step-time breakdown the ISSUE asks for
    — compile (first step), ingest (per-shard device_put dispatch; the
    transfers themselves overlap compute), steady step time. Also
    records the ``gather`` schedule, the ANALYTIC peak live-param bytes
    for that schedule (parallel/sharding.param_residency_bytes — gates
    identically on CPU and TPU), and, on fsdp meshes, the measured cost
    of one full-tree gather/scatter probe (the collective the streamed
    schedule hides inside compute).

    ``mfu`` here is STANDARD MFU (attention FLOPs included, the
    PaLM/Chinchilla definition); ``mfu_params_only`` is the
    conservative 6ND-only numerator the headline section reports.
    """
    import jax
    import numpy as np

    from ray_tpu.parallel.sharding import (param_residency_bytes,
                                           shard_device_put)
    from ray_tpu.train.spmd import (make_collective_probes,
                                    make_spmd_train_step,
                                    spmd_param_specs)

    n_dev = mesh.size
    init, step, data_sharding, _ = make_spmd_train_step(
        cfg, mesh, donate=donate, gather=gather)
    state = init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    pool = [rng.randint(0, cfg.vocab_size,
                        (batch, seq + 1)).astype(np.int32)
            for _ in range(4)]

    has_fsdp = "fsdp" in mesh.axis_names
    gather_eff = gather if has_fsdp else "upfront"  # the step's fold
    sample, specs = spmd_param_specs(cfg, mesh)
    residency = param_residency_bytes(sample, specs, mesh, mode=gather_eff)

    probe_ms = {}
    if has_fsdp:
        gp, sp = make_collective_probes(cfg, mesh)
        for name, fn in (("gather_probe_ms", gp), ("scatter_probe_ms", sp)):
            jax.block_until_ready(fn(state["params"]))  # compile
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(state["params"]))
                best = min(best, time.perf_counter() - t0)
            probe_ms[name] = round(1e3 * best, 3)

    parity = None
    if gspmd_parity:
        # same seed + same first batch through the GSPMD step: the two
        # programs must produce the same first-step loss
        from ray_tpu.models.llama import make_train_step

        ginit, gstep, gds, _ = make_train_step(cfg, mesh)
        gstate = ginit(jax.random.PRNGKey(0))
        _, gloss = gstep(gstate, jax.device_put(pool[0], gds))
        parity = float(gloss)
        del gstate

    # compile + warmup (sync via host fetch; see run_config note)
    t0 = time.perf_counter()
    state, loss = step(state, shard_device_put(pool[0], data_sharding))
    first_loss = float(loss)
    compile_s = time.perf_counter() - t0
    for i in range(2):
        state, loss = step(state, shard_device_put(pool[i % 4],
                                                   data_sharding))
    float(loss)

    # timed: double-buffered ingest — batch N+1 is placed (per-shard,
    # async dispatch) before batch N's step result is awaited
    ingest_s = 0.0
    t0 = time.perf_counter()
    ti = time.perf_counter()
    pending = shard_device_put(pool[0], data_sharding)
    ingest_s += time.perf_counter() - ti
    for i in range(steps):
        toks = pending
        ti = time.perf_counter()
        pending = shard_device_put(pool[(i + 1) % 4], data_sharding)
        ingest_s += time.perf_counter() - ti
        state, loss = step(state, toks)
    final_loss = float(loss)
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    n_params = cfg.num_params()
    model_flops = 6.0 * n_params * tokens_per_sec
    attn_flops = (6.0 * cfg.n_layers * cfg.n_heads * seq * cfg.head_dim
                  * tokens_per_sec)
    peak = peak_flops_per_chip() * n_dev
    out = {
        "platform": jax.devices()[0].platform,
        "devices": n_dev,
        "mesh": {k: int(v) for k, v in dict(mesh.shape).items()},
        "params_m": round(n_params / 1e6, 1),
        "batch": batch,
        "seq": seq,
        "steps": steps,
        "donate": bool(donate),
        "gather": gather_eff,
        "peak_live_param_bytes": residency["peak_bytes"],
        "shard_param_bytes": residency["shard_bytes"],
        "tokens_per_sec": round(tokens_per_sec, 2),
        "tokens_per_sec_per_chip": round(tokens_per_sec / n_dev, 2),
        "mfu": round((model_flops + attn_flops) / peak, 4),
        "mfu_params_only": round(model_flops / peak, 4),
        "breakdown": {
            "compile_s": round(compile_s, 3),
            "ingest_dispatch_ms_per_step": round(1e3 * ingest_s / steps, 3),
            "step_ms": round(1e3 * dt / steps, 3),
            **probe_ms,
        },
        "first_loss": round(first_loss, 6),
        "final_loss": round(final_loss, 6),
    }
    if parity is not None:
        out["gspmd_first_loss"] = round(parity, 6)
        out["loss_parity_rel"] = round(
            abs(first_loss - parity) / max(abs(parity), 1e-9), 6)
    print(f"# sharded mesh={out['mesh']} devices={n_dev} batch={batch} "
          f"seq={seq} mfu={out['mfu']:.3f} "
          f"tok/s/chip={out['tokens_per_sec_per_chip']:.0f} "
          f"step={out['breakdown']['step_ms']:.1f}ms "
          f"ingest={out['breakdown']['ingest_dispatch_ms_per_step']:.2f}ms",
          file=sys.stderr)
    return out


def spmd_bench(args):
    """--spmd-bench: sharded-train sweep over device counts →
    BENCH_SPMD.json with a --check gate.

    Each device count runs in a fresh subprocess: real accelerators
    when the host has that many chips, else virtual CPU devices (the
    --devices re-exec; the CHILD decides, and reports its platform in
    the run record — the gates below key off what was actually
    measured, never the parent's platform). Gates:

    - parity: sharded first-step loss == GSPMD first-step loss (same
      seed/batch) within 2% at every device count;
    - scaling: weak-scaling throughput flat or better as devices grow.
      On real accelerators that is tokens/s/chip (each chip has its own
      silicon); on a shared-core virtual CPU mesh N devices split one
      host's compute, so the honest flat-line is TOTAL tokens/s
      (= per-chip × N, the "host-normalized per-chip" rate) — raw
      per-chip numbers on virtual devices measure core oversubscription,
      not SPMD overhead;
    - ingest: per-shard device_put dispatch stays under 25% of step
      time (the transfer itself overlaps compute);
    - mfu: >= 0.55 at devices=1 on TPU hardware, re-attempted over the
      donation x batch tune sweep's best row. On CPU there is no
      hardware peak to hold the step to, so the gate is recorded as
      not-applicable (the committed artifact carries the measured CPU
      mfu for trend only; BENCH_r0N carries the TPU number);
    - streamed_vs_upfront: the per-layer streamed gather schedule is no
      slower than the upfront bulk gather at devices>=4 — enforced on
      hardware, trend-only on CPU (oversubscribed virtual devices
      time-slice the overlap away);
    - live_param_bytes: streamed peak live-param bytes strictly below
      upfront (analytic residency model — enforced on every platform);
    - schema: every run record carries the keys future PRs gate on.
    """
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))

    def child(n, batch=None, extra_env=None):
        argv = [sys.executable, os.path.abspath(sys.argv[0]),
                "--spmd", "--devices", str(n), "--steps", str(args.steps)]
        if args.config != "bench":
            argv += ["--config", args.config]
        if batch or args.batch:
            argv += ["--batch", str(batch or args.batch)]
        if args.seq:
            argv += ["--seq", str(args.seq)]
        env = dict(os.environ)
        env.update(extra_env or {})
        proc = subprocess.run(argv, capture_output=True, text=True,
                              cwd=here, env=env)
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0:
            raise RuntimeError(f"spmd child devices={n} failed "
                               f"rc={proc.returncode}")
        return json.loads(proc.stdout.strip().splitlines()[-1])

    devices = [int(d) for d in (args.spmd_devices or "1,2,4").split(",")]
    runs = [child(n) for n in devices]

    # donation x per-chip-batch tune at the largest device count: the
    # knobs that move sharded MFU without touching the model. Children
    # skip the A/B re-run (_RAY_TPU_SPMD_NO_AB) — the sweep prices the
    # knobs, not the schedules.
    tune_rows = []
    n_max = devices[-1]
    base_batch = args.batch or 8
    for don, bpc in ((True, base_batch), (True, base_batch * 2),
                     (True, base_batch * 4), (False, base_batch * 2)):
        rec = child(n_max, batch=bpc, extra_env={
            "RAY_TPU_TRAIN_DONATE": "1" if don else "0",
            "_RAY_TPU_SPMD_NO_AB": "1"})
        tune_rows.append({
            "devices": rec["devices"],
            "platform": rec.get("platform", "cpu"),
            "donate": don,
            "batch_per_chip": bpc,
            "tokens_per_sec_per_chip": rec["tokens_per_sec_per_chip"],
            "mfu": rec["mfu"],
            "step_ms": rec["breakdown"]["step_ms"],
        })
    tune_best = max(tune_rows, key=lambda r: r["tokens_per_sec_per_chip"])

    # gates key off what each child actually measured on (run records
    # carry the platform), never this parent process's platform
    platforms = {r.get("platform", "cpu") for r in runs}
    base = runs[0]
    gates = {}
    # parity reference (GSPMD step, same seed/batch) runs on the CPU
    # children only — hardware runs gate on MFU/scaling instead
    rels = [r["loss_parity_rel"] for r in runs if "loss_parity_rel" in r]
    gates["parity"] = {
        "worst_rel": max(rels) if rels else None,
        "limit": 0.02,
        "runs_with_parity": len(rels),
        "ok": all(r <= 0.02 for r in rels),
    }
    # weak scaling: fixed per-chip batch, so the flat line is total
    # tokens/s on a shared-core virtual mesh, per-chip on real chips.
    # Ratios compare WITHIN a platform group only (a sweep that spills
    # past the real chip count mixes TPU and CPU-fallback children —
    # cross-platform ratios would gate one platform against the other's
    # throughput and fail spuriously); each group scales vs its own
    # smallest-device run.
    groups: dict = {}
    for r in runs:
        groups.setdefault(r.get("platform", "cpu"), []).append(r)
    ratio_rows = []
    for plat, rs in sorted(groups.items()):
        key = ("tokens_per_sec" if plat == "cpu"
               else "tokens_per_sec_per_chip")
        limit = 0.75 if plat == "cpu" else 0.9
        b = rs[0]
        for r in rs[1:]:
            ratio_rows.append({
                "platform": plat,
                "devices": r["devices"],
                "metric": key,
                "ratio_vs_smallest": round(r[key] / b[key], 4),
                "limit": limit,
            })
    gates["scaling_flat"] = {
        "note": "cpu gates on total tokens/s (virtual devices share "
                "the host cores; per-chip would measure "
                "oversubscription); hardware gates on tokens/s/chip",
        "ratios": ratio_rows,
        "ok": all(r["ratio_vs_smallest"] >= r["limit"]
                  for r in ratio_rows),
    }
    ingest_frac = [
        r["breakdown"]["ingest_dispatch_ms_per_step"]
        / max(r["breakdown"]["step_ms"], 1e-9) for r in runs]
    gates["ingest_overlap"] = {
        "dispatch_frac": [round(f, 4) for f in ingest_frac],
        "limit": 0.25,
        "ok": all(f <= 0.25 for f in ingest_frac),
    }
    hw_runs = [r for r in runs if r.get("platform", "cpu") != "cpu"]
    hw_tune = [r for r in tune_rows if r["platform"] != "cpu"]
    if hw_runs:
        hw_base = min(hw_runs, key=lambda r: r["devices"])
        best_mfu = max([hw_base["mfu"]] + [r["mfu"] for r in hw_tune])
        gates["mfu"] = {"value": best_mfu,
                        "devices": hw_base["devices"], "target": 0.55,
                        "note": "best of base run and tune sweep",
                        "ok": best_mfu >= 0.55}
    else:
        gates["mfu"] = {
            "value": max([base["mfu"]] + [r["mfu"] for r in tune_rows]),
            "target": 0.55,
            "ok": True,
            "note": "target applies on TPU hardware; CPU has no HW peak "
                    "to hold the step to — see BENCH_r0N 'sharded' for "
                    "the TPU number",
        }

    # upfront-vs-streamed A/B: streamed must not be slower where the
    # overlap can actually happen (real chips, devices>=4); virtual CPU
    # devices time-slice one host's cores, so collectives and matmuls
    # can't genuinely overlap — those rows record the trend only. The
    # analytic residency gate holds everywhere.
    ab_rows = []
    for r in runs:
        ab = r.get("gather_ab")
        if not ab:
            continue
        ab_rows.append({
            "devices": r["devices"],
            "platform": r.get("platform", "cpu"),
            "streamed_step_ms": ab["streamed"]["step_ms"],
            "upfront_step_ms": ab["upfront"]["step_ms"],
            "step_ratio": round(ab["streamed"]["step_ms"]
                                / max(ab["upfront"]["step_ms"], 1e-9), 4),
            "streamed_bytes": ab["streamed"]["peak_live_param_bytes"],
            "upfront_bytes": ab["upfront"]["peak_live_param_bytes"],
            "overlap_ratio": ab["overlap_ratio"],
        })
    hw_ab = [r for r in ab_rows
             if r["platform"] != "cpu" and r["devices"] >= 4]
    gates["streamed_vs_upfront"] = {
        "rows": ab_rows,
        "limit": 1.0,
        "note": "streamed step <= upfront at devices>=4, enforced on "
                "hardware; cpu virtual meshes record the trend (shared "
                "cores time-slice the overlap away)",
        "ok": bool(ab_rows) and all(r["step_ratio"] <= 1.0 for r in hw_ab),
    }
    gates["live_param_bytes"] = {
        "rows": [{"devices": r["devices"], "streamed": r["streamed_bytes"],
                  "upfront": r["upfront_bytes"]} for r in ab_rows],
        "note": "analytic residency model — platform-independent",
        "ok": bool(ab_rows) and all(
            r["streamed_bytes"] < r["upfront_bytes"] for r in ab_rows),
    }

    # schema: the keys future PRs gate on must exist in every record
    run_keys = ("platform", "devices", "gather", "peak_live_param_bytes",
                "shard_param_bytes", "tokens_per_sec_per_chip", "mfu")
    ab_keys = ("upfront", "streamed", "overlap_ratio")
    missing = [f"run[devices={r.get('devices')}].{k}"
               for r in runs for k in run_keys if k not in r]
    missing += [f"gather_ab[devices={r['devices']}].{k}"
                for r in runs if "gather_ab" in r
                for k in ab_keys if k not in r["gather_ab"]]
    if not any("gather_ab" in r for r in runs):
        missing.append("gather_ab (no A/B ran — need a devices>=2 row)")
    if not tune_rows:
        missing.append("tune.rows")
    gates["schema"] = {"required_run_keys": list(run_keys),
                       "missing": missing, "ok": not missing}

    out = {
        "bench": "spmd_sharded_train",
        "platform": "+".join(sorted(platforms)),
        "runs": runs,
        "tune": {
            "note": "donate x batch-per-chip sweep at the largest device "
                    "count (A/B skipped in these children)",
            "rows": tune_rows,
            "best": tune_best,
        },
        "gates": gates,
        "check": all(g["ok"] for g in gates.values()),
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_SPMD.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps({"metric": "spmd_sharded_train", "check": out["check"],
                      "gates": {k: g["ok"] for k, g in gates.items()},
                      "path": path}))
    if args.check and not out["check"]:
        raise SystemExit(1)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="tiny config for CPU smoke-testing")
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--batch", type=int, default=0,
                        help="GLOBAL batch for the GSPMD sections; the "
                        "--spmd/--spmd-bench weak-scaling sweep "
                        "interprets it PER-CHIP (global = batch x "
                        "devices), so the per-chip workload stays fixed "
                        "as devices grow — don't compare numbers across "
                        "the two modes at the 'same' --batch")
    parser.add_argument("--seq", type=int, default=0)
    parser.add_argument("--config", default="bench",
                        choices=["debug", "small", "medium", "bench",
                                 "flagship"])
    parser.add_argument("--no-flagship", action="store_true",
                        help="skip the flagship (1B, bf16-mu adam) pass "
                        "that normally runs alongside the bench config "
                        "on TPU")
    parser.add_argument("--devices", type=int, default=0,
                        help="run on N virtual CPU devices (re-execs with "
                        "xla_force_host_platform_device_count=N) to measure "
                        "the multi-chip GSPMD step; 0 = local devices")
    parser.add_argument("--mesh", default="",
                        help="axis spec for --devices runs, e.g. "
                        "'fsdp=2,seq=2,tensor=2' (default fsdp=N)")
    parser.add_argument("--spmd", action="store_true",
                        help="run ONLY the sharded-train section "
                        "(train/spmd.py shard_map step) and print its "
                        "JSON line")
    parser.add_argument("--spmd-bench", action="store_true",
                        help="sharded-train sweep over --spmd-devices "
                        "-> BENCH_SPMD.json")
    parser.add_argument("--spmd-devices", default="",
                        help="comma list of device counts for "
                        "--spmd-bench (default 1,2,4)")
    parser.add_argument("--check", action="store_true",
                        help="exit 1 if a BENCH_SPMD gate fails")
    args = parser.parse_args()

    if args.spmd_bench and os.environ.get("_RAY_TPU_BENCH_CHILD") != "1":
        spmd_bench(args)
        return

    if args.devices and os.environ.get("_RAY_TPU_BENCH_CHILD") != "1":
        # real accelerators win when the host has enough of them: only
        # re-exec onto a virtual CPU mesh (shared host cores — measures
        # oversubscription, not silicon) as the fallback
        import jax as _jax

        if (_jax.devices()[0].platform == "cpu"
                or len(_jax.devices()) < args.devices):
            import subprocess

            env = dict(os.environ)
            env["_RAY_TPU_BENCH_CHILD"] = "1"
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
            flags = [f for f in env.get("XLA_FLAGS", "").split()
                     if "xla_force_host_platform_device_count" not in f]
            flags.append(
                f"--xla_force_host_platform_device_count={args.devices}")
            env["XLA_FLAGS"] = " ".join(flags)
            argv = [os.path.abspath(sys.argv[0])] + sys.argv[1:]
            raise SystemExit(subprocess.run(
                [sys.executable] + argv, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__))).returncode)

    import jax
    import numpy as np

    from ray_tpu.models.llama import LlamaConfig, make_train_step
    from ray_tpu.parallel import MeshConfig, make_mesh

    n_dev = len(jax.devices())
    if args.devices:
        # honor the requested count on hosts with more real chips
        # (make_mesh slices devices[:product])
        n_dev = min(n_dev, args.devices)
    on_cpu = args.quick or jax.devices()[0].platform == "cpu"
    if on_cpu:
        # CPU (incl. --devices virtual mesh): debug config unless the user
        # explicitly picked one small enough to step on host
        cfg = (LlamaConfig.debug() if args.config == "bench"
               else getattr(LlamaConfig, args.config)())
        batch, seq, steps = 8, 128, max(3, args.steps // 4)
    else:
        cfg = getattr(LlamaConfig, args.config)()
        batch = {"medium": 8, "bench": 8, "flagship": 8}.get(args.config, 16)
        seq, steps = 2048, args.steps
    if args.batch:
        batch = args.batch
    if args.seq:
        seq = args.seq

    # single-host mesh over all local chips: fsdp over chips (or --mesh spec)
    axes = {"data": 1, "fsdp": n_dev, "seq": 1, "tensor": 1}
    if args.mesh:
        axes = {"data": 1, "fsdp": 1, "seq": 1, "tensor": 1}
        for part in args.mesh.split(","):
            k, v = part.split("=")
            axes[k.strip()] = int(v)
    mesh = make_mesh(MeshConfig(**axes))
    n_dev = mesh.size  # per-chip metrics count only devices in the mesh

    if args.spmd:
        # sharded-train section: shard_map step + partition rules +
        # donated state + overlapped per-shard ingest (train/spmd.py).
        # Default layout: pure data-parallel over the mesh's devices
        # (weak scaling — fixed per-chip batch); --mesh may add fsdp.
        smesh = mesh if args.mesh else make_mesh(
            axis_sizes={"data": n_dev})
        per_chip = args.batch or (8 if on_cpu else 16)
        from ray_tpu.core.config import global_config

        res = measure_sharded(
            cfg, smesh, per_chip * smesh.size, seq, steps,
            donate=global_config().train_donate,
            gspmd_parity=on_cpu,
            gather=global_config().train_gather)
        if (smesh.size >= 2
                and os.environ.get("_RAY_TPU_SPMD_NO_AB") != "1"):
            # upfront-vs-streamed A/B on an fsdp mesh (streamed folds to
            # upfront without one). The streamed schedule only holds
            # FEWER bytes when the stack has more layers than its
            # 2-layer gather window, so shallow debug configs get their
            # layer count raised for the A/B — the numbers compare the
            # two schedules against each other, not against the primary
            # run above.
            import dataclasses

            ab_cfg = (cfg if cfg.n_layers > 2
                      else dataclasses.replace(cfg, n_layers=6))
            ab_mesh = (smesh if "fsdp" in smesh.axis_names
                       else make_mesh(axis_sizes={"fsdp": smesh.size}))
            ab = {}
            for mode in ("upfront", "streamed"):
                r = measure_sharded(
                    ab_cfg, ab_mesh, per_chip * ab_mesh.size, seq, steps,
                    donate=global_config().train_donate, gather=mode)
                ab[mode] = {
                    "step_ms": r["breakdown"]["step_ms"],
                    "peak_live_param_bytes": r["peak_live_param_bytes"],
                    "tokens_per_sec_per_chip": r["tokens_per_sec_per_chip"],
                    "gather_probe_ms": r["breakdown"].get("gather_probe_ms"),
                }
            probe = ab["streamed"]["gather_probe_ms"] or 0.0
            extra = max(0.0, ab["streamed"]["step_ms"]
                        - ab["upfront"]["step_ms"])
            # fraction of one full-tree gather the streamed schedule
            # hides inside compute: 1.0 = fully overlapped (streamed no
            # slower than upfront), 0.0 = the whole gather cost shows
            # up as extra step time
            overlap = (max(0.0, min(1.0, (probe - extra) / probe))
                       if probe > 0 else None)
            res["gather_ab"] = {
                "mesh": {k: int(v) for k, v in dict(ab_mesh.shape).items()},
                "n_layers": ab_cfg.n_layers,
                "upfront": ab["upfront"],
                "streamed": ab["streamed"],
                "overlap_ratio": (round(overlap, 4)
                                  if overlap is not None else None),
            }
        print(json.dumps(res))
        return

    def run_config(cfg, batch, seq, steps, flagship=False):
        """Measure one training config; returns the metrics dict."""
        optimizer = None
        if flagship:
            import optax

            # adafactor, bf16 momentum: the T5/PaLM TPU recipe. Peak HBM
            # = fp32 params (4 B) + fp32 grads (4 B) + bf16 momentum (2 B)
            # + factored second moment (~0) ~= 10 B/param; the bf16-mu
            # adamw variant peaks at 14 B/param (fp32 nu + grads) and
            # OOMs the 16 GB chip above ~950M params.
            optimizer = optax.adafactor(
                learning_rate=3e-4, momentum=0.9,
                dtype_momentum=jax.numpy.bfloat16)
        init, step, data_sharding, _ = make_train_step(
            cfg, mesh, optimizer=optimizer)
        state = init(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        tokens = jax.device_put(
            rng.randint(0, cfg.vocab_size,
                        (batch, seq + 1)).astype(np.int32),
            data_sharding)
        # warmup (compile) then timed steps. NOTE: sync via host fetch —
        # block_until_ready is a no-op on the experimental axon platform.
        for _ in range(3):
            state, loss = step(state, tokens)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss = step(state, tokens)
        final_loss = float(loss)
        dt = time.perf_counter() - t0

        tokens_per_sec = batch * seq * steps / dt
        n_params = cfg.num_params()
        model_flops = 6.0 * n_params * tokens_per_sec  # fwd+bwd matmuls
        # causal attention matmul FLOPs: fwd 2*(QK^T)+2*(PV) halved by
        # causality = 2*H*T*D per token, tripled for bwd (dq + dkv)
        attn_flops = (6.0 * cfg.n_layers * cfg.n_heads * seq * cfg.head_dim
                      * tokens_per_sec)
        peak = peak_flops_per_chip() * n_dev
        mfu = model_flops / peak  # conservative: params-only numerator
        mfu_attn = (model_flops + attn_flops) / peak
        print(f"# cfg={cfg.dim}d/{cfg.n_layers}L "
              f"params={n_params/1e6:.1f}M batch={batch} seq={seq} "
              f"steps={steps} dt={dt:.2f}s mfu={mfu:.3f} "
              f"mfu_with_attn={mfu_attn:.3f} loss={final_loss:.3f} "
              f"devices={n_dev}", file=sys.stderr)
        return {
            "params_m": round(n_params / 1e6, 1),
            "tokens_per_sec_per_chip": round(tokens_per_sec / n_dev, 2),
            "mfu": round(mfu, 4),
            "mfu_with_attn": round(mfu_attn, 4),
            "vs_baseline": round(mfu / 0.35, 4),
        }

    primary = run_config(cfg, batch, seq, steps,
                         flagship=(args.config == "flagship"))
    out = {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": primary["tokens_per_sec_per_chip"],
        "unit": "tokens/s/chip",
        "vs_baseline": primary["vs_baseline"],
    }
    if not on_cpu:
        # ride-along sharded-train section on hardware (ISSUE 14 gate:
        # standard MFU >= 0.55 at devices=1): shard_map step, donated
        # state, overlapped per-shard ingest, batch 16/chip. Never
        # loses the headline on failure.
        try:
            from ray_tpu.core.config import global_config

            _compile_cleanup()
            smesh = make_mesh(axis_sizes={"data": n_dev})
            out["sharded"] = run_with_compile_retries(
                lambda: measure_sharded(
                    cfg, smesh, 16 * n_dev, seq, max(5, args.steps // 2),
                    donate=global_config().train_donate))
        except Exception as e:  # noqa: BLE001 — headline survives
            out["sharded"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    # the flagship pass (1B, the largest single-v5e-chip config) rides
    # along on real hardware: BENCH_r{N} then carries both the 664M trend
    # line and the flagship MFU (round-4 VERDICT ask #10)
    if (not on_cpu and args.config == "bench" and not args.no_flagship
            and not args.batch and not args.seq):
        # fallback ladder: full 1.04B, then the largest config that fits
        # with the heavier bf16-mu adamw state (2048d/14L, 924M) — the
        # committed artifact must carry a live flagship number even if
        # the compile environment regresses (round-4 VERDICT ask #2)
        ladder = [
            ("flagship_1040m", LlamaConfig.flagship()),
            ("fallback_924m", LlamaConfig(
                vocab_size=32000, dim=2048, n_layers=14, n_heads=16,
                n_kv_heads=8, mlp_dim=7168, max_seq_len=2048)),
        ]
        errors = []
        # the bench pass's compiled executables/buffers must not crowd
        # the flagship compile (BENCH_r04: helper subprocess exit 1)
        _compile_cleanup()
        for name, fcfg in ladder:
            try:
                out["flagship"] = run_with_compile_retries(
                    lambda fcfg=fcfg: run_config(fcfg, 8, 2048,
                                                 max(5, args.steps // 2),
                                                 flagship=True))
                out["flagship"]["config"] = name
                break
            except Exception as e:  # noqa: BLE001 — never lose the headline
                errors.append(f"{name}: {type(e).__name__}: {e}"[:200])
        else:
            out["flagship"] = {"error": " | ".join(errors)[:400]}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
