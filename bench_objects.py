"""Object data-plane microbenchmark: put/get latency + 2-node transfer MB/s.

Prints ONE JSON line (same convention as bench.py):

    {"bench": "objects", "put_ms": {"1KB": .., "1MB": .., "64MB": ..},
     "get_ms": {...}, "transfer_MBps": {"1KB": .., "1MB": .., "64MB": ..},
     "pool": {"hits": N, "misses": N}}

- put/get: driver <-> local node store (inline for 1KB, arena for the rest).
- transfer: a REAL separate-process daemon node produces the payload; the
  driver pulls it over the node-to-node object plane (the path rebuilt by
  the zero-copy data-plane PR: pooled connections + arena-direct receive +
  striped pulls). MB/s = payload bytes / wall-clock pull time.

Runs under ``JAX_PLATFORMS=cpu`` (no accelerator needed).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# arena headroom: the 64 MB series keeps a few payloads live at once
os.environ.setdefault("RAY_TPU_OBJECT_STORE_MEMORY", str(1 << 30))

SIZES = {"1KB": 1 << 10, "1MB": 1 << 20, "64MB": 64 << 20}


def _median_ms(samples):
    return round(statistics.median(samples) * 1000.0, 3)


def bench_put_get(iters):
    import numpy as np

    import ray_tpu

    put_ms, get_ms = {}, {}
    for label, size in SIZES.items():
        n = max(3, iters // (8 if size >= (1 << 20) else 1))
        puts, gets = [], []
        for _ in range(n):
            arr = np.ones(size, dtype=np.uint8)
            t0 = time.perf_counter()
            ref = ray_tpu.put(arr)
            t1 = time.perf_counter()
            out = ray_tpu.get(ref)
            t2 = time.perf_counter()
            assert out.nbytes == size
            puts.append(t1 - t0)
            gets.append(t2 - t1)
            del ref, out
        put_ms[label] = _median_ms(puts)
        get_ms[label] = _median_ms(gets)
    return put_ms, get_ms


def bench_transfer(iters):
    """Daemon node -> driver pull throughput (2 OS processes, real TCP)."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    cluster.add_node(num_cpus=1, resources={"src": 4},
                     separate_process=True)

    @ray_tpu.remote(resources={"src": 1})
    def produce(nbytes, salt):
        import numpy as np

        a = np.empty(nbytes, dtype=np.uint8)
        a[:] = salt & 0xFF
        return a

    out = {}
    try:
        # warm the worker + transfer path once
        ray_tpu.get(produce.remote(1024, 0), timeout=120)
        for label, size in SIZES.items():
            n = max(2, iters // (8 if size >= (1 << 20) else 1))
            rates = []
            for i in range(n):
                ref = produce.remote(size, i + 1)
                # materialize on the producer before timing the pull
                ray_tpu.wait([ref], timeout=120, fetch_local=False)
                t0 = time.perf_counter()
                arr = ray_tpu.get(ref, timeout=300)
                dt = time.perf_counter() - t0
                assert arr.nbytes == size and int(arr[0]) == (i + 1) & 0xFF
                rates.append(size / dt / (1 << 20))
                del arr, ref
            out[label] = round(statistics.median(rates), 1)
    finally:
        cluster.shutdown()
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=24,
                    help="samples for the small sizes (large sizes use /8)")
    ap.add_argument("--skip-transfer", action="store_true")
    args = ap.parse_args()

    import ray_tpu

    ray_tpu.init(num_cpus=2, num_tpus=0)
    try:
        put_ms, get_ms = bench_put_get(args.iters)
    finally:
        ray_tpu.shutdown()

    transfer = {} if args.skip_transfer else bench_transfer(args.iters)

    try:
        from ray_tpu.core import object_transfer

        pool = object_transfer.pool_stats()
    except Exception:
        pool = {}
    print(json.dumps({"bench": "objects", "put_ms": put_ms,
                      "get_ms": get_ms, "transfer_MBps": transfer,
                      "pool": pool}))


if __name__ == "__main__":
    main()
