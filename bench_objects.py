"""Object data-plane microbenchmark: put/get latency + 2-node transfer MB/s.

Prints ONE JSON line (same convention as bench.py):

    {"bench": "objects", "put_ms": {"1KB": .., "1MB": .., "64MB": ..},
     "get_ms": {...}, "transfer_MBps": {"1KB": .., "1MB": .., "64MB": ..},
     "pool": {"hits": N, "misses": N}}

- put/get: driver <-> local node store (inline for 1KB, arena for the rest).
- transfer: a REAL separate-process daemon node produces the payload; the
  driver pulls it over the node-to-node object plane (the path rebuilt by
  the zero-copy data-plane PR: pooled connections + arena-direct receive +
  striped pulls). MB/s = payload bytes / wall-clock pull time.

``--check`` instead runs the memory-observability overhead gate: put/get
p50 with ref accounting fully off (RAY_TPU_REF_ACCOUNTING_ENABLED=0)
vs on (the default) vs on+callsites (RAY_TPU_RECORD_REF_CREATION_SITES=1),
one subprocess per rep with modes interleaved and per-metric min-of-rounds
(single-round p50 on a shared 1.5-core box swings far more than the
~1 dict-op cost being measured). Budgets: accounting <= 3% over off,
callsites <= 10%. Writes BENCH_MEMORY.json via --out.

Runs under ``JAX_PLATFORMS=cpu`` (no accelerator needed).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# arena headroom: the 64 MB series keeps a few payloads live at once
os.environ.setdefault("RAY_TPU_OBJECT_STORE_MEMORY", str(1 << 30))

SIZES = {"1KB": 1 << 10, "1MB": 1 << 20, "64MB": 64 << 20}


def _median_ms(samples):
    return round(statistics.median(samples) * 1000.0, 3)


def bench_put_get(iters):
    import numpy as np

    import ray_tpu

    put_ms, get_ms = {}, {}
    for label, size in SIZES.items():
        n = max(3, iters // (8 if size >= (1 << 20) else 1))
        puts, gets = [], []
        for _ in range(n):
            arr = np.ones(size, dtype=np.uint8)
            t0 = time.perf_counter()
            ref = ray_tpu.put(arr)
            t1 = time.perf_counter()
            out = ray_tpu.get(ref)
            t2 = time.perf_counter()
            assert out.nbytes == size
            puts.append(t1 - t0)
            gets.append(t2 - t1)
            del ref, out
        put_ms[label] = _median_ms(puts)
        get_ms[label] = _median_ms(gets)
    return put_ms, get_ms


def bench_transfer(iters):
    """Daemon node -> driver pull throughput (2 OS processes, real TCP)."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    cluster.add_node(num_cpus=1, resources={"src": 4},
                     separate_process=True)

    @ray_tpu.remote(resources={"src": 1})
    def produce(nbytes, salt):
        import numpy as np

        a = np.empty(nbytes, dtype=np.uint8)
        a[:] = salt & 0xFF
        return a

    out = {}
    try:
        # warm the worker + transfer path once
        ray_tpu.get(produce.remote(1024, 0), timeout=120)
        for label, size in SIZES.items():
            n = max(2, iters // (8 if size >= (1 << 20) else 1))
            rates = []
            for i in range(n):
                ref = produce.remote(size, i + 1)
                # materialize on the producer before timing the pull
                ray_tpu.wait([ref], timeout=120, fetch_local=False)
                t0 = time.perf_counter()
                arr = ray_tpu.get(ref, timeout=300)
                dt = time.perf_counter() - t0
                assert arr.nbytes == size and int(arr[0]) == (i + 1) & 0xFF
                rates.append(size / dt / (1 << 20))
                del arr, ref
            out[label] = round(statistics.median(rates), 1)
    finally:
        cluster.shutdown()
    return out


# ---- memory-observability overhead gate (--check) ------------------------ #

OVERHEAD_SIZES = {"1KB": 1 << 10, "1MB": 1 << 20}
MODES = {
    # mode -> (REF_ACCOUNTING_ENABLED, RECORD_REF_CREATION_SITES)
    "off": ("0", "0"),
    "on": ("1", "0"),
    "sites": ("1", "1"),
}


def run_overhead_phase(iters: int) -> dict:
    """One mode, in-process (the parent set the env gates before python
    started, so the config snapshot and the tracker flag cache both see
    them). Several rounds, keep each round's put/get median, report the
    per-size MIN across rounds."""
    import numpy as np

    import ray_tpu

    ray_tpu.init(num_cpus=1, num_tpus=0)
    try:
        # warmup: allocator, serializer caches, ref-tracker lazy init
        for _ in range(10):
            ray_tpu.get(ray_tpu.put(np.ones(1 << 10, dtype=np.uint8)))
        rounds, out_put, out_get = 3, {}, {}
        per = max(20, iters)
        for label, size in OVERHEAD_SIZES.items():
            p50s_put, p50s_get = [], []
            for _ in range(rounds):
                puts, gets = [], []
                for _ in range(per):
                    arr = np.ones(size, dtype=np.uint8)
                    t0 = time.perf_counter()
                    ref = ray_tpu.put(arr)
                    t1 = time.perf_counter()
                    out = ray_tpu.get(ref)
                    t2 = time.perf_counter()
                    assert out.nbytes == size
                    puts.append(t1 - t0)
                    gets.append(t2 - t1)
                    del ref, out, arr
                p50s_put.append(_median_ms(puts))
                p50s_get.append(_median_ms(gets))
            out_put[label] = min(p50s_put)
            out_get[label] = min(p50s_get)
        return {"put_p50_ms": out_put, "get_p50_ms": out_get}
    finally:
        ray_tpu.shutdown()


def _spawn_overhead_phase(mode: str, iters: int) -> dict:
    acct, sites = MODES[mode]
    env = dict(os.environ)
    env["RAY_TPU_REF_ACCOUNTING_ENABLED"] = acct
    env["RAY_TPU_RECORD_REF_CREATION_SITES"] = sites
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--phase", mode,
         "--iters", str(iters)],
        env=env, capture_output=True, text=True, timeout=600)
    if out.returncode != 0:
        raise RuntimeError(
            f"phase {mode} failed:\n{out.stdout}\n{out.stderr}")
    for line in reversed(out.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(f"phase {mode} printed no JSON:\n{out.stdout}")


def run_overhead_gate(args) -> int:
    # interleave modes across reps (rotating which goes first, so cold-
    # start/thermal bias can't land on one mode); per-metric min across
    # reps x rounds is the noise-robust stat for a shared CI box
    order = list(MODES)
    runs = {m: [] for m in MODES}
    for rep in range(max(1, args.reps)):
        rot = order[rep % len(order):] + order[:rep % len(order)]
        for mode in rot:
            runs[mode].append(_spawn_overhead_phase(mode, args.iters))

    def best(mode):
        return {op: {sz: min(r[op][sz] for r in runs[mode])
                     for sz in OVERHEAD_SIZES}
                for op in ("put_p50_ms", "get_p50_ms")}

    modes = {m: best(m) for m in MODES}

    def overhead(mode):
        worst = None
        for op in ("put_p50_ms", "get_p50_ms"):
            for sz in OVERHEAD_SIZES:
                base = modes["off"][op][sz]
                if not base:
                    continue
                pct = (modes[mode][op][sz] - base) / base * 100.0
                if worst is None or pct > worst:
                    worst = pct
        return round(worst, 2) if worst is not None else None

    result = {
        "bench": "memory_overhead",
        "iters": args.iters, "reps": args.reps,
        "modes": modes,
        # worst put/get p50 regression vs accounting-off, per gated mode
        "overhead_accounting_pct": overhead("on"),
        "overhead_callsites_pct": overhead("sites"),
        "budget_accounting_pct": args.budget_pct,
        "budget_callsites_pct": args.budget_sites_pct,
    }
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f)
    rc = 0
    oh_on = result["overhead_accounting_pct"]
    if oh_on is not None and oh_on > args.budget_pct:
        print(f"FAIL: ref-accounting put/get p50 overhead {oh_on}% > "
              f"{args.budget_pct}% budget", file=sys.stderr)
        rc = 1
    oh_sites = result["overhead_callsites_pct"]
    if oh_sites is not None and oh_sites > args.budget_sites_pct:
        print(f"FAIL: callsite-capture put/get p50 overhead {oh_sites}% > "
              f"{args.budget_sites_pct}% budget", file=sys.stderr)
        rc = 1
    return rc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=24,
                    help="samples for the small sizes (large sizes use /8)")
    ap.add_argument("--skip-transfer", action="store_true")
    ap.add_argument("--check", action="store_true",
                    help="run the ref-accounting overhead gate instead of "
                         "the data-plane bench; exit 1 over budget")
    ap.add_argument("--phase", choices=list(MODES),
                    help="internal: run one overhead mode in-process")
    ap.add_argument("--reps", type=int, default=3,
                    help="interleaved subprocess reps per mode (--check)")
    ap.add_argument("--budget-pct", type=float, default=3.0,
                    help="p50 budget for accounting-on, callsites-off")
    ap.add_argument("--budget-sites-pct", type=float, default=10.0,
                    help="p50 budget for accounting-on + callsites-on")
    ap.add_argument("--out", help="also write the gate JSON here (--check)")
    args = ap.parse_args()

    if args.phase:
        print(json.dumps(run_overhead_phase(args.iters)))
        return 0
    if args.check:
        return run_overhead_gate(args)

    import ray_tpu

    ray_tpu.init(num_cpus=2, num_tpus=0)
    try:
        put_ms, get_ms = bench_put_get(args.iters)
    finally:
        ray_tpu.shutdown()

    transfer = {} if args.skip_transfer else bench_transfer(args.iters)

    try:
        from ray_tpu.core import object_transfer

        pool = object_transfer.pool_stats()
    except Exception:
        pool = {}
    print(json.dumps({"bench": "objects", "put_ms": put_ms,
                      "get_ms": get_ms, "transfer_MBps": transfer,
                      "pool": pool}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
