"""Data ingest microbenchmarks: operator fusion + zero-copy rechunk.

Prints ONE JSON line (same convention as bench.py / bench_serve.py):

    {"bench": "data",
     "fused":   {"rows_per_s": .., "store_puts": ..},
     "unfused": {"rows_per_s": .., "store_puts": ..},
     "fusion_speedup": ..,
     "puts_bound": <stages x blocks>,
     "rechunk": {"short_us_per_batch": .., "long_us_per_batch": ..,
                 "cost_ratio": ..}}

Pipeline bench: rows/s through read -> map_batches -> map_batches ->
iter_batches on a fresh cluster per rep. Each mode runs in its OWN
subprocess (the fusion knob is snapshotted by pools/caches, and a fresh
interpreter per rep keeps reps independent); fused/unfused reps are
INTERLEAVED and the per-mode MAX of rows/s (i.e. min runtime) is
reported — this box is ~1.5 cores and noisy, scheduling luck swings a
single rep far more than the effect being measured.

The fused phase also reports object-store puts observed in the driver
registry: fusion's mechanism is materializing ONE block per chain
instead of one per stage, so fused puts must come in under
stages x blocks (the unfused floor).

Rechunk bench: iter_batches over pre-materialized in-process blocks at
two stream lengths; per-batch cost must be flat in stream length (the
old carry re-concat grew linearly -> quadratic total).

``--check`` exits non-zero when fused rows/s regresses below unfused
(--min-speedup, default 1.0) or the rechunk per-batch cost ratio
exceeds --max-rechunk-ratio (default 3.0: generous noise allowance on
a cost that used to scale ~8x at these stream lengths).

Runs under ``JAX_PLATFORMS=cpu`` (no accelerator needed).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

ROWS = 200_000
BLOCKS = 8
STAGES = 3  # read + 2 map_batches


def _store_puts() -> float:
    from ray_tpu.util.metrics import registry

    m = registry().snapshot().get("ray_tpu_object_store_puts_total")
    return sum(m["values"].values()) if m else 0.0


def run_pipeline_phase(rows: int, blocks: int) -> dict:
    import ray_tpu
    from ray_tpu import data as rd

    ray_tpu.init(num_cpus=4, num_tpus=0)
    ds = (rd.range(rows, parallelism=blocks)
          .map_batches(lambda b: {"id": b["id"] * 2}, batch_format="numpy")
          .map_batches(lambda b: {"id": b["id"] + 1}, batch_format="numpy"))
    # warmup: worker pool spin-up, function registration, first-run jits
    sum(len(b["id"]) for b in rd.range(
        rows // 10, parallelism=blocks).map_batches(
        lambda b: {"id": b["id"]}, batch_format="numpy")
        .iter_batches(batch_size=4096, batch_format="numpy"))

    puts_before = _store_puts()
    t0 = time.perf_counter()
    seen = 0
    for batch in ds.iter_batches(batch_size=4096, batch_format="numpy",
                                 prefetch_batches=2):
        seen += len(batch["id"])
    dt = time.perf_counter() - t0
    puts = _store_puts() - puts_before
    assert seen == rows, (seen, rows)
    ray_tpu.shutdown()
    return {"rows_per_s": round(rows / dt, 1), "elapsed_s": round(dt, 4),
            "store_puts": puts}


def run_rechunk_phase() -> dict:
    """Per-batch rechunk cost at two stream lengths, pure in-process
    (no cluster): the iterator's BlockBuffer against synthetic blocks."""
    import numpy as np

    from ray_tpu.data.block import block_from_numpy
    from ray_tpu.data.iterator import BlockBuffer

    def bench(n_blocks: int, rounds: int = 5) -> float:
        rows_per_block, batch = 1000, 900  # misaligned -> spanning batches
        blocks = [block_from_numpy(
            {"x": np.arange(rows_per_block, dtype=np.int64)})
            for _ in range(n_blocks)]
        best = float("inf")
        for _ in range(rounds):
            buf = BlockBuffer()
            batches = 0
            t0 = time.perf_counter()
            for b in blocks:
                buf.add_block(b)
                while buf.num_rows() >= batch:
                    buf.take(batch)
                    batches += 1
            while buf.num_rows():
                buf.take(min(batch, buf.num_rows()))
                batches += 1
            dt = time.perf_counter() - t0
            best = min(best, dt / batches * 1e6)
        return best

    short = bench(40)
    long_ = bench(320)
    return {"short_us_per_batch": round(short, 2),
            "long_us_per_batch": round(long_, 2),
            "cost_ratio": round(long_ / short, 3)}


def _spawn_phase(mode: str, rows: int, blocks: int) -> dict:
    env = dict(os.environ)
    env["RAY_TPU_DATA_FUSION"] = "1" if mode == "fused" else "0"
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--phase", "pipeline",
         "--rows", str(rows), "--blocks", str(blocks)],
        env=env, capture_output=True, text=True, timeout=600)
    if out.returncode != 0:
        raise RuntimeError(
            f"phase {mode} failed:\n{out.stdout}\n{out.stderr}")
    for line in reversed(out.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(f"phase {mode} printed no JSON:\n{out.stdout}")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=ROWS)
    ap.add_argument("--blocks", type=int, default=BLOCKS)
    ap.add_argument("--reps", type=int, default=3,
                    help="interleaved repetitions per mode; best rep "
                         "(min runtime) is reported")
    ap.add_argument("--phase", choices=["pipeline"],
                    help="internal: run one pipeline rep in-process")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on fused-vs-unfused regression or "
                         "rechunk cost growth")
    ap.add_argument("--min-speedup", type=float, default=1.0)
    ap.add_argument("--max-rechunk-ratio", type=float, default=3.0)
    ap.add_argument("--out", default="BENCH_DATA.json",
                    help="also write the JSON result here ('' = skip)")
    args = ap.parse_args()

    if args.phase == "pipeline":
        print(json.dumps(run_pipeline_phase(args.rows, args.blocks)))
        return 0

    results = {"fused": [], "unfused": []}
    for rep in range(args.reps):  # interleave modes inside each rep
        for mode in ("fused", "unfused"):
            r = _spawn_phase(mode, args.rows, args.blocks)
            results[mode].append(r)
            print(f"# rep {rep} {mode}: {r}", file=sys.stderr)

    def best(mode: str) -> dict:
        by_time = min(results[mode], key=lambda r: r["elapsed_s"])
        return {"rows_per_s": by_time["rows_per_s"],
                "elapsed_s": by_time["elapsed_s"],
                "store_puts": min(r["store_puts"] for r in results[mode])}

    fused, unfused = best("fused"), best("unfused")
    rechunk = run_rechunk_phase()
    out = {
        "bench": "data",
        "rows": args.rows,
        "blocks": args.blocks,
        "fused": fused,
        "unfused": unfused,
        "fusion_speedup": round(
            fused["rows_per_s"] / unfused["rows_per_s"], 3),
        "puts_bound": STAGES * args.blocks,
        "rechunk": rechunk,
    }
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")

    ok = True
    if args.check:
        if fused["store_puts"] >= STAGES * args.blocks:
            print(f"# FAIL: fused store puts {fused['store_puts']} >= "
                  f"stages x blocks = {STAGES * args.blocks}",
                  file=sys.stderr)
            ok = False
        if out["fusion_speedup"] < args.min_speedup:
            print(f"# FAIL: fusion speedup {out['fusion_speedup']} < "
                  f"{args.min_speedup}", file=sys.stderr)
            ok = False
        if rechunk["cost_ratio"] > args.max_rechunk_ratio:
            print(f"# FAIL: rechunk cost ratio {rechunk['cost_ratio']} > "
                  f"{args.max_rechunk_ratio}", file=sys.stderr)
            ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
